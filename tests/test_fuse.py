"""Plane-major multi-query fusion tests (exec/plan.py interpreter +
exec/coalesce.py program-key tier + executor wiring).

The acceptance bar: a mixed storm of DISTINCT Count/Range/TopN trees is
byte-identical across the fused, coalesce-only, and direct paths
(including BSI predicates at declared min/max boundaries); identical
queries within a fused batch share one lowered program and the emitter
dedups shared subtrees; a tree that exceeds the opcode-table bucket
falls back to the per-compile-key coalesce path rather than failing;
and a concurrent storm's launches stay well under its query count, with
the interpreter program-cache entries flat as mix diversity grows.
"""

import concurrent.futures
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from pilosa_tpu import bsi
from pilosa_tpu.cluster.topology import new_cluster
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import Executor, plan
from pilosa_tpu.exec.coalesce import CoalesceScheduler
from pilosa_tpu.ops.bitplane import SLICE_WIDTH, WORDS_PER_SLICE
from pilosa_tpu.pql.parser import parse_string

WAIT_US = 200_000


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def _canon(result):
    if hasattr(result, "bits"):
        return ("bits", tuple(result.bits()))
    if isinstance(result, list):
        return ("pairs", tuple((p.id, p.count) for p in result))
    return ("val", int(result))


# ---------------------------------------------------------------------------
# lowering + interpreter units
# ---------------------------------------------------------------------------


def test_emitter_value_numbering_dedups_commuted_subtrees():
    em = plan.FuseEmitter(4)
    a = em.and_(0, 1)
    b = em.and_(1, 0)  # commutative operand order normalizes
    assert a == b and em.dedup_hits == 1
    c = em.andnot(0, 1)
    d = em.andnot(1, 0)  # andnot is NOT commutative
    assert c != d
    assert em.maskw(2, 3) == em.maskw(2, 3)
    assert em.dedup_hits == 2


def test_emitter_rollback_restores_table():
    em = plan.FuseEmitter(2, max_ops=4)
    em.and_(0, 1)
    cp = em.checkpoint()
    em.or_(0, 1)
    em.xor(0, 1)
    em.rollback(cp)
    assert len(em.rows) == 1
    # memo entries past the checkpoint are gone: re-emitting allocates
    # fresh registers instead of referencing truncated ones.
    r = em.or_(0, 1)
    assert r == em.n_leaves + 1


def test_emitter_op_budget_raises():
    em = plan.FuseEmitter(2, max_ops=2)
    em.and_(0, 1)
    em.or_(0, 1)
    with pytest.raises(plan.FuseUnsupported):
        em.xor(0, 1)


def test_lower_expr_matches_eval_expr_np_random(rng):
    """Randomized trees (folds over leaves, nested) evaluate
    byte-identically between the interpreter and the numpy host
    reference."""
    words = 128
    exprs = [
        ("leaf", 0),
        ("Intersect", ("leaf", 0), ("leaf", 1)),
        ("Union", ("leaf", 0), ("Intersect", ("leaf", 1), ("leaf", 2))),
        ("Difference", ("leaf", 0), ("leaf", 1), ("leaf", 2)),
        ("Xor", ("Union", ("leaf", 0), ("leaf", 1)), ("leaf", 2)),
        (
            "Intersect",
            ("Union", ("leaf", 0), ("leaf", 1)),
            ("Difference", ("leaf", 2), ("leaf", 3)),
        ),
    ]
    for expr in exprs:
        n_leaves = max(_max_leaf(expr) + 1, 1)
        leaf_rows = [
            rng.integers(0, 2**32, size=words, dtype=np.uint32)
            for _ in range(n_leaves)
        ]
        want = plan.eval_expr_np(expr, leaf_rows, words)
        if want is None:
            want = np.zeros(words, dtype=np.uint32)
        em = plan.FuseEmitter(n_leaves)
        reg = plan.lower_expr(expr, 0, em)
        n_ops = max(len(em.rows), 1)
        prog = np.zeros((n_ops, 4), dtype=np.int32)
        if em.rows:
            prog[: len(em.rows)] = np.asarray(em.rows, dtype=np.int32)
        batch = np.stack(leaf_rows)[None]
        got = np.asarray(
            plan.interp_exec(
                "row", batch, prog, np.asarray([reg], dtype=np.int32)
            )
        )[0, 0]
        np.testing.assert_array_equal(got, want)


def _max_leaf(expr) -> int:
    if expr[0] == "leaf":
        return expr[1]
    return max((_max_leaf(e) for e in expr[1:] if isinstance(e, tuple)), default=0)


def test_lower_bsi_cmp_matches_ripple_all_ops(rng):
    """The lowered BSI ripple is byte-identical to the array ripple for
    every comparison op, positive and negative predicates included."""
    words = 64
    depth = 8
    exists = np.full(words, 0xFFFFFFFF, np.uint32)
    sign = rng.integers(0, 2**32, size=words, dtype=np.uint32)
    planes = rng.integers(0, 2**32, size=(depth, words), dtype=np.uint32)
    cases = [
        ("lt", 100), ("le", 0), ("eq", 37), ("ne", -3),
        ("ge", -120), ("gt", 255),
    ]
    for op, v in cases:
        pred = bsi.pred_row(v, depth)[: words]
        expr = ("bsiCmp", op) + tuple(("leaf", i) for i in range(depth + 3))
        leaf_rows = [exists, sign, *planes, pred]
        want = plan.eval_expr_np(expr, leaf_rows, words)
        em = plan.FuseEmitter(len(leaf_rows))
        reg = plan.lower_expr(expr, 0, em)
        prog = np.asarray(em.rows, dtype=np.int32)
        batch = np.stack(leaf_rows)[None]
        got = np.asarray(
            plan.interp_exec(
                "row", batch, prog, np.asarray([reg], dtype=np.int32)
            )
        )[0, 0]
        np.testing.assert_array_equal(got, want, err_msg=f"op={op} v={v}")


def test_lower_between_shares_subtrees(rng):
    """between = two ripples; the emitter's value numbering shares the
    sign-group rows between them (dedup fires)."""
    words = 32
    depth = 8
    exists = np.full(words, 0xFFFFFFFF, np.uint32)
    sign = rng.integers(0, 2**32, size=words, dtype=np.uint32)
    planes = rng.integers(0, 2**32, size=(depth, words), dtype=np.uint32)
    lo, hi = bsi.pred_row(-10, depth)[:words], bsi.pred_row(99, depth)[:words]
    expr = ("bsiCmp", "between") + tuple(
        ("leaf", i) for i in range(depth + 4)
    )
    leaf_rows = [exists, sign, *planes, lo, hi]
    want = plan.eval_expr_np(expr, leaf_rows, words)
    em = plan.FuseEmitter(len(leaf_rows))
    reg = plan.lower_expr(expr, 0, em)
    assert em.dedup_hits > 0  # pos/neg sign groups shared across ripples
    prog = np.asarray(em.rows, dtype=np.int32)
    batch = np.stack(leaf_rows)[None]
    got = np.asarray(
        plan.interp_exec("row", batch, prog, np.asarray([reg], np.int32))
    )[0, 0]
    np.testing.assert_array_equal(got, want)


def test_lower_bsi_aggregate_unsupported():
    expr = ("bsiSum", False) + tuple(("leaf", i) for i in range(10))
    with pytest.raises(plan.FuseUnsupported):
        plan.lower_expr(expr, 0, plan.FuseEmitter(10))


def test_canonicalize_call_commutes_and_preserves_difference():
    q1 = parse_string(
        "TopN(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)),"
        " frame=t, n=2)"
    ).calls[0]
    q2 = parse_string(
        "TopN(Intersect(Bitmap(rowID=2, frame=f), Bitmap(rowID=1, frame=f)),"
        " frame=t, n=2)"
    ).calls[0]
    assert str(plan.canonicalize_call(q1)) == str(plan.canonicalize_call(q2))
    d1 = parse_string(
        "Difference(Bitmap(rowID=2, frame=f), Bitmap(rowID=1, frame=f))"
    ).calls[0]
    # Difference is not commutative: child order survives.
    assert str(plan.canonicalize_call(d1)) == str(d1)
    # Unchanged trees return the original object (cache keys stay
    # byte-identical for already-canonical queries).
    c = parse_string("Count(Bitmap(rowID=1, frame=f))").calls[0]
    assert plan.canonicalize_call(c) is c


# ---------------------------------------------------------------------------
# scheduler fusion
# ---------------------------------------------------------------------------


def test_fused_launch_distinct_exprs_one_launch(rng):
    co = CoalesceScheduler(max_wait_us=WAIT_US)
    try:
        words = 64
        batches = [
            jnp.asarray(
                rng.integers(0, 2**32, size=(4, 2, words), dtype=np.uint32)
            )
            for _ in range(3)
        ]
        exprs = [
            ("Intersect", ("leaf", 0), ("leaf", 1)),
            ("Union", ("leaf", 0), ("leaf", 1)),
            ("Xor", ("leaf", 0), ("leaf", 1)),
        ]
        futs = [
            co.submit(e, "count", b) for e, b in zip(exprs, batches)
        ]
        results = [f.result(timeout=30) for f in futs]
        fns = (np.bitwise_and, np.bitwise_or, np.bitwise_xor)
        for (res, info), b, fn in zip(results, batches, fns):
            h = np.asarray(b)
            want = np.bitwise_count(fn(h[:, 0], h[:, 1])).sum(axis=-1)
            np.testing.assert_array_equal(res, want)
            assert info["fused"] and info["programs"] == 3
        assert len({r[1]["launch"] for r in results}) == 1
        snap = co.snapshot()
        assert snap["fused_launches"] == 1
        assert snap["fused_queries"] == 3
    finally:
        co.close()


def test_fused_launch_identical_queries_share_program(rng):
    """N waiters of one (expr, batch) + M distinct queries: the
    identical ones share a single lowered program (identical leaf sets
    evaluated once) — programs counts DISTINCT trees, not waiters."""
    co = CoalesceScheduler(max_wait_us=WAIT_US)
    try:
        words = 32
        b1 = jnp.asarray(
            rng.integers(0, 2**32, size=(2, 2, words), dtype=np.uint32)
        )
        b2 = jnp.asarray(
            rng.integers(0, 2**32, size=(2, 2, words), dtype=np.uint32)
        )
        e1 = ("Intersect", ("leaf", 0), ("leaf", 1))
        e2 = ("Union", ("leaf", 0), ("leaf", 1))
        futs = [co.submit(e1, "count", b1) for _ in range(5)]
        futs.append(co.submit(e2, "count", b2))
        results = [f.result(timeout=30) for f in futs]
        info = results[0][1]
        assert info["fused"]
        assert info["batch_queries"] == 6
        assert info["programs"] == 2  # 5 identical waiters -> 1 program
        h1 = np.asarray(b1)
        want = np.bitwise_count(h1[:, 0] & h1[:, 1]).sum(axis=-1)
        for res, _ in results[:5]:
            np.testing.assert_array_equal(res, want)
    finally:
        co.close()


def test_union_leaf_sharing_collapses_columns(rng):
    """Two DISTINCT queries whose batches carry the same leaf identity
    key share ONE union register — the fused pass streams the shared
    plane row once (shared_leaves counts the collapse), and a common
    subtree over shared leaves dedups ACROSS the two queries."""
    co = CoalesceScheduler(max_wait_us=WAIT_US)
    try:
        words = 64
        rows = rng.integers(0, 2**32, size=(3, words), dtype=np.uint32)
        b1 = jnp.asarray(np.stack([rows[0], rows[1]])[None])  # [1, 2, w]
        b2 = jnp.asarray(np.stack([rows[0], rows[1], rows[2]])[None])
        k0, k1, k2 = ("r", 0), ("r", 1), ("r", 2)
        e1 = ("Intersect", ("leaf", 0), ("leaf", 1))
        e2 = ("Xor", ("Intersect", ("leaf", 0), ("leaf", 1)), ("leaf", 2))
        f1 = co.submit(e1, "count", b1, leaf_keys=(k0, k1))
        f2 = co.submit(e2, "count", b2, leaf_keys=(k0, k1, k2))
        (r1, i1), (r2, i2) = f1.result(timeout=30), f2.result(timeout=30)
        assert int(r1[0]) == int(np.bitwise_count(rows[0] & rows[1]).sum())
        assert int(r2[0]) == int(
            np.bitwise_count((rows[0] & rows[1]) ^ rows[2]).sum()
        )
        assert i1["fused"] and i1["programs"] == 2
        # 5 raw columns collapse to the 3-leaf union.
        assert i1["leaf_rows"] == 3 and i1["shared_leaves"] == 2
        # q2's Intersect(l0, l1) subtree reuses q1's lowered op.
        assert i1["dedup_hits"] >= 1
        assert co.snapshot()["fuse_shared_leaves"] == 2
    finally:
        co.close()


def test_fuse_row_reduce_scatters_rows(rng):
    co = CoalesceScheduler(max_wait_us=WAIT_US)
    try:
        words = 32
        b1 = jnp.asarray(
            rng.integers(0, 2**32, size=(2, 2, words), dtype=np.uint32)
        )
        b2 = jnp.asarray(
            rng.integers(0, 2**32, size=(2, 3, words), dtype=np.uint32)
        )
        e1 = ("Intersect", ("leaf", 0), ("leaf", 1))
        e2 = ("Union", ("leaf", 0), ("leaf", 1), ("leaf", 2))
        f1 = co.submit(e1, "row", b1)
        f2 = co.submit(e2, "row", b2)
        (r1, i1), (r2, i2) = f1.result(timeout=30), f2.result(timeout=30)
        h1, h2 = np.asarray(b1), np.asarray(b2)
        np.testing.assert_array_equal(r1, h1[:, 0] & h1[:, 1])
        np.testing.assert_array_equal(r2, h2[:, 0] | h2[:, 1] | h2[:, 2])
        assert i1["fused"] and i1["leaf_rows"] == 5 and i1["pad_leaves"] == 3
    finally:
        co.close()


def test_fuse_oversized_tree_falls_back_to_coalesce(rng):
    """A tree whose lowering exceeds the opcode-table bucket rides the
    ordinary per-compile-key concat launch — correct results, fused
    counters untouched for it, fallback counter incremented."""
    co = CoalesceScheduler(max_wait_us=WAIT_US)
    try:
        words = 16
        n_leaves = plan.FUSE_MAX_OPS + 2  # fold ops = n_leaves - 1 > budget
        big = jnp.asarray(
            rng.integers(
                0, 2**32, size=(1, n_leaves, words), dtype=np.uint32
            )
        )
        small = jnp.asarray(
            rng.integers(0, 2**32, size=(1, 2, words), dtype=np.uint32)
        )
        big_expr = ("Union",) + tuple(("leaf", i) for i in range(n_leaves))
        small_expr = ("Intersect", ("leaf", 0), ("leaf", 1))
        f_big = co.submit(big_expr, "count", big)
        f_small = co.submit(small_expr, "count", small)
        (rb, ib) = f_big.result(timeout=60)
        (rs, _is) = f_small.result(timeout=60)
        hb, hs = np.asarray(big), np.asarray(small)
        want_b = np.bitwise_count(
            np.bitwise_or.reduce(hb[0], axis=0)
        ).sum()
        np.testing.assert_array_equal(rb, [want_b])
        np.testing.assert_array_equal(
            rs, np.bitwise_count(hs[:, 0] & hs[:, 1]).sum(axis=-1)
        )
        assert not ib.get("fused")
        assert co.snapshot()["fuse_fallbacks"] >= 1
    finally:
        co.close()


def test_fuse_disabled_keeps_concat_semantics(rng):
    co = CoalesceScheduler(max_wait_us=WAIT_US, fuse=False)
    try:
        words = 16
        b1 = jnp.asarray(
            rng.integers(0, 2**32, size=(1, 2, words), dtype=np.uint32)
        )
        b2 = jnp.asarray(
            rng.integers(0, 2**32, size=(1, 2, words), dtype=np.uint32)
        )
        f1 = co.submit(("Intersect", ("leaf", 0), ("leaf", 1)), "count", b1)
        f2 = co.submit(("Union", ("leaf", 0), ("leaf", 1)), "count", b2)
        (r1, i1) = f1.result(timeout=30)
        (r2, _) = f2.result(timeout=30)
        h1, h2 = np.asarray(b1), np.asarray(b2)
        assert int(r1[0]) == int(np.bitwise_count(h1[:, 0] & h1[:, 1]).sum())
        assert int(r2[0]) == int(np.bitwise_count(h2[:, 0] | h2[:, 1]).sum())
        assert not i1.get("fused")
        assert co.snapshot()["fused_launches"] == 0
    finally:
        co.close()


def test_shared_fetch_batches_round_trips(rng):
    co = CoalesceScheduler(max_wait_us=WAIT_US)
    try:
        arrs = [
            jnp.asarray(
                rng.integers(0, 2**32, size=(4, 8), dtype=np.uint32)
            )
            for _ in range(4)
        ]
        futs = [co.submit_fetch([a]) for a in arrs]
        results = [f.result(timeout=30) for f in futs]
        for (hosts, info), a in zip(results, arrs):
            np.testing.assert_array_equal(np.asarray(hosts[0]), np.asarray(a))
        # All four items drained in one device_get round trip.
        assert len({r[1]["fetch_launch"] for r in results}) == 1
        assert co.snapshot()["fetch_launches"] == 1
    finally:
        co.close()


# ---------------------------------------------------------------------------
# executor integration: mixed storms byte-identical across all paths
# ---------------------------------------------------------------------------

BSI_MIN, BSI_MAX = -128, 127


def _seed_mixed(holder, rng):
    idx = holder.create_index("i")
    f = idx.create_frame("f", cache_size=64)
    bits = [
        (1, 0), (1, 3), (1, SLICE_WIDTH + 1), (1, 2 * SLICE_WIDTH + 5),
        (2, 3), (2, SLICE_WIDTH + 1), (2, SLICE_WIDTH + 9),
        (3, 7), (3, 2 * SLICE_WIDTH + 5), (4, 11), (4, SLICE_WIDTH + 2),
    ]
    for row, col in bits:
        f.set_bit("standard", row, col)
    f.set_options(range_enabled=True)
    f.create_field("v", BSI_MIN, BSI_MAX)
    vals = {}
    for col in range(0, 3 * SLICE_WIDTH, SLICE_WIDTH // 7):
        v = int(rng.integers(BSI_MIN, BSI_MAX + 1))
        vals[col] = v
        f.import_value("v", [col], [v])
    ft = idx.create_frame("t", cache_size=64)
    for row in range(6):
        for col in range(0, 2 * SLICE_WIDTH, SLICE_WIDTH // (5 + row)):
            ft.set_bit("standard", row, col)
    return vals


# Mixed distinct trees: point counts, rows, BSI ranges INCLUDING the
# declared min/max boundaries, and TopN(src).
MIXED = [
    "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))",
    "Count(Union(Bitmap(rowID=1, frame=f), Bitmap(rowID=3, frame=f)))",
    "Count(Difference(Bitmap(rowID=2, frame=f), Bitmap(rowID=4, frame=f)))",
    "Bitmap(rowID=1, frame=f)",
    "Union(Bitmap(rowID=2, frame=f), Bitmap(rowID=3, frame=f))",
    f"Count(Range(frame=f, v > {BSI_MIN}))",
    f"Count(Range(frame=f, v >= {BSI_MIN}))",
    f"Count(Range(frame=f, v < {BSI_MAX}))",
    f"Count(Range(frame=f, v <= {BSI_MAX}))",
    "Count(Range(frame=f, v == 0))",
    f"Count(Range(frame=f, v >< [{BSI_MIN}, {BSI_MAX}]))",
    "Count(Range(frame=f, v > 17))",
    "Count(Intersect(Bitmap(rowID=1, frame=f), Range(frame=f, v < -5)))",
    "TopN(Bitmap(rowID=0, frame=t), frame=t, n=3)",
    "TopN(frame=t, n=2)",
]


def test_mixed_storm_byte_identical_fused_coalesce_direct(holder, rng):
    _seed_mixed(holder, rng)
    c = new_cluster(1)
    host = c.nodes[0].host
    plain = Executor(holder, host=host, cluster=c)
    try:
        expected = [
            _canon(plain.execute("i", parse_string(q))[0]) for q in MIXED
        ]
    finally:
        plain.close()

    for fuse_on in (False, True):
        co = CoalesceScheduler(max_wait_us=WAIT_US, fuse=fuse_on)
        ex = Executor(holder, host=host, cluster=c, coalescer=co)
        try:
            got = [
                _canon(ex.execute("i", parse_string(q))[0]) for q in MIXED
            ]
            assert got == expected, f"serial fuse={fuse_on}"

            def run_mix(t):
                # Stagger each thread's starting point so DISTINCT
                # trees co-queue (lockstep threads would only ever
                # exercise identity dedup).
                order = list(range(t, len(MIXED))) + list(range(t))
                got = [None] * len(MIXED)
                for i in order:
                    got[i] = _canon(ex.execute("i", parse_string(MIXED[i]))[0])
                return got

            with concurrent.futures.ThreadPoolExecutor(8) as pool:
                for got in pool.map(run_mix, range(8)):
                    assert got == expected, f"concurrent fuse={fuse_on}"
            if fuse_on:
                snap = co.snapshot()
                assert snap["fused_launches"] >= 1
                assert snap["fused_queries"] > snap["fused_launches"]
        finally:
            ex.close()
            co.close()


def test_concurrent_distinct_storm_launches_far_below_queries(holder, rng):
    """The headline invariant: a storm of DISTINCT queries rides far
    fewer launches than queries via the fusion tier (the old coalescer
    could only do this for identical queries)."""
    _seed_mixed(holder, rng)
    c = new_cluster(1)
    co = CoalesceScheduler(max_wait_us=WAIT_US)
    ex = Executor(holder, host=c.nodes[0].host, cluster=c, coalescer=co)
    try:
        queries = [
            parse_string(q)
            for q in MIXED
            if q.startswith("Count(") or q.startswith("Bitmap")
        ]
        # Warm every distinct batch cache entry serially.
        want = [_canon(ex.execute("i", q)[0]) for q in queries]
        before = co.snapshot()
        n = 48
        barrier = threading.Barrier(12)

        def one(i):
            barrier.wait(timeout=30)
            q = queries[i % len(queries)]
            assert _canon(ex.execute("i", q)[0]) == want[i % len(queries)]

        with concurrent.futures.ThreadPoolExecutor(12) as pool:
            list(pool.map(one, range(n)))
        snap = co.snapshot()
        launches = snap["launches"] - before["launches"]
        qn = snap["queries"] - before["queries"]
        assert qn == n
        assert launches < qn, (launches, qn)
        assert snap["fused_queries"] - before["fused_queries"] > 0
    finally:
        ex.close()
        co.close()


def test_interp_program_cache_flat_under_diversity(holder, rng):
    """exec.programCache.entries[cache:interp] is O(1) in mix
    diversity: doubling the distinct-predicate mix adds NO interpreter
    entries (opcode tables are data; geometry is the only jit key)."""
    _seed_mixed(holder, rng)
    c = new_cluster(1)
    co = CoalesceScheduler(max_wait_us=WAIT_US)
    ex = Executor(holder, host=c.nodes[0].host, cluster=c, coalescer=co)
    try:
        def storm(preds):
            queries = [
                parse_string(f"Count(Range(frame=f, v > {p}))") for p in preds
            ]
            for q in queries:
                ex.execute("i", q)
            barrier = threading.Barrier(8)

            def one(i):
                barrier.wait(timeout=30)
                ex.execute("i", queries[i % len(queries)])

            with concurrent.futures.ThreadPoolExecutor(8) as pool:
                list(pool.map(one, range(16)))

        storm((1, 2, 3, 4))
        entries = plan.program_cache_stats()["interp"]
        assert entries >= 1
        bounds = plan.program_cache_bounds()
        assert entries <= bounds["interp"]
        # Same tree GEOMETRY, brand-new predicates: zero new compiles.
        storm((11, 22, 33, 44, 55, 66, 77, 88))
        assert plan.program_cache_stats()["interp"] == entries
        assert plan.program_cache_stats()["interp"] <= (
            plan.program_cache_bounds()["interp"]
        )
    finally:
        ex.close()
        co.close()


def test_topn_canonical_key_shares_single_flight(holder, rng):
    """PR-10 single-flight keyed on the exact query string; the
    canonical compile key shares one dispatch across semantically
    identical TopN(src) queries whose src trees merely commute — and
    the results stay byte-identical."""
    _seed_mixed(holder, rng)
    c = new_cluster(1)
    ex = Executor(holder, host=c.nodes[0].host, cluster=c)
    try:
        q1 = parse_string(
            "TopN(Union(Bitmap(rowID=0, frame=t), Bitmap(rowID=1, frame=t)),"
            " frame=t, n=3)"
        )
        q2 = parse_string(
            "TopN(Union(Bitmap(rowID=1, frame=t), Bitmap(rowID=0, frame=t)),"
            " frame=t, n=3)"
        )
        (r1,) = ex.execute("i", q1)
        # Byte-identity across orderings.
        (r2,) = ex.execute("i", q2)
        assert _canon(r1) == _canon(r2)
        # The prep cache holds ONE entry for both orderings (the
        # canonical key), so the second ordering validated against the
        # first's entry instead of building its own.
        keys = list(ex._topn_cache.keys())
        assert len([k for k in keys if "Union" in k[1]]) == 1
    finally:
        ex.close()


def test_topn_commuted_storm_one_dispatch(holder, rng):
    """Concurrent commuted-ordering TopN storm: every query shares the
    leader's fetched scores (exec.topn.scoreShared fires; one entry)."""
    _seed_mixed(holder, rng)

    class CountingStats:
        def __init__(self):
            self.counts = {}

        def count(self, name, value=1, rate=1.0):
            self.counts[name] = self.counts.get(name, 0) + value

        def count_with_custom_tags(self, name, value, tags):
            self.count(name, value)

        def gauge(self, *a, **k):
            pass

        def histogram(self, *a, **k):
            pass

        def timing(self, *a, **k):
            pass

        def tags(self):
            return []

    holder.stats = CountingStats()
    c = new_cluster(1)
    ex = Executor(holder, host=c.nodes[0].host, cluster=c)
    try:
        texts = [
            "TopN(Union(Bitmap(rowID=2, frame=t), Bitmap(rowID=3, frame=t)),"
            " frame=t, n=3)",
            "TopN(Union(Bitmap(rowID=3, frame=t), Bitmap(rowID=2, frame=t)),"
            " frame=t, n=3)",
        ]
        queries = [parse_string(t) for t in texts]
        (want,) = ex.execute("i", queries[0])
        barrier = threading.Barrier(8)

        def one(i):
            barrier.wait(timeout=30)
            (got,) = ex.execute("i", queries[i % 2])
            assert _canon(got) == _canon(want)

        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            list(pool.map(one, range(16)))
        # Both orderings rode the one validated entry: score sharing
        # fired (without canonicalization the second ordering would
        # have built its own entry and never shared).
        assert holder.stats.counts.get("exec.topn.scoreShared", 0) > 0
        union_keys = [k for k in ex._topn_cache if "Union" in k[1]]
        assert len(union_keys) == 1
    finally:
        ex.close()
