"""Property-based tests — the analog of the reference's testing/quick
suites (reference: server/server_test.go:43-122 TestMain_Set_Quick,
roaring/roaring_test.go randomized tests): random operation sequences
validated against a pure-Python set model.
"""

import numpy as np
import pytest

# The property tier needs hypothesis; environments without it (minimal
# CI images) skip the whole module instead of erroring at collection.
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from pilosa_tpu.core.bitmap import RowBitmap
from pilosa_tpu.ops import roaring

# PILOSA_TPU_QUICK_EXAMPLES scales the property tier into a soak run
# (e.g. =500 for hours-long shakeouts); default stays CI-fast.
import os

QUICK = settings(
    max_examples=int(os.environ.get("PILOSA_TPU_QUICK_EXAMPLES", "25")),
    deadline=None,
    suppress_health_check=[HealthCheck.function_scoped_fixture],
)

bit_positions = st.lists(
    st.integers(min_value=0, max_value=2**20 - 1), min_size=0, max_size=300
)


# ---------------------------------------------------------------------------
# roaring codec
# ---------------------------------------------------------------------------


container_dicts = st.dictionaries(
    st.integers(min_value=0, max_value=500),
    st.lists(st.integers(min_value=0, max_value=2**16 - 1), max_size=200),
    max_size=8,
)


from tests.conftest import positions_to_words as _to_words


class TestRoaringProperties:
    @QUICK
    @given(container_dicts)
    def test_encode_decode_roundtrip(self, d):
        containers = {k: _to_words(v) for k, v in d.items()}
        nonempty = {k: w for k, w in containers.items() if w.any()}
        back = roaring.decode(roaring.encode(containers))
        assert sorted(back) == sorted(nonempty)
        for k, w in nonempty.items():
            np.testing.assert_array_equal(back[k], w)

    @QUICK
    @given(
        container_dicts,
        st.lists(
            st.tuples(
                st.sampled_from([roaring.OP_ADD, roaring.OP_REMOVE]),
                st.integers(min_value=0, max_value=2**24),
            ),
            max_size=50,
        ),
    )
    def test_oplog_replay_matches_model(self, d, ops):
        containers = {k: _to_words(v) for k, v in d.items()}
        data = roaring.encode(containers)
        model = set()
        for k, w in containers.items():
            if not w.any():
                continue
            for p in np.nonzero(np.unpackbits(w.view(np.uint8), bitorder="little"))[0]:
                model.add(k * 2**16 + int(p))
        for typ, value in ops:
            data += roaring.encode_op(typ, value)
            if typ == roaring.OP_ADD:
                model.add(value)
            else:
                model.discard(value)
        back = roaring.decode(data)
        got = set()
        for k, w in back.items():
            for p in np.nonzero(np.unpackbits(w.view(np.uint8), bitorder="little"))[0]:
                got.add(k * 2**16 + int(p))
        assert got == model


# ---------------------------------------------------------------------------
# RowBitmap algebra vs python sets
# ---------------------------------------------------------------------------


class TestRowBitmapProperties:
    @QUICK
    @given(bit_positions, bit_positions)
    def test_algebra_matches_sets(self, a_bits, b_bits):
        # spread across two slices to exercise the segment merge
        a_bits = [b + (b % 2) * 2**20 for b in a_bits]
        b_bits = [b + (b % 3 == 0) * 2**20 for b in b_bits]
        a, b = RowBitmap.from_bits(a_bits), RowBitmap.from_bits(b_bits)
        sa, sb = set(a_bits), set(b_bits)
        from pilosa_tpu.net.codec import bitmap_to_json

        assert bitmap_to_json(a.intersect(b))["bits"] == sorted(sa & sb)
        assert bitmap_to_json(a.union(b))["bits"] == sorted(sa | sb)
        assert bitmap_to_json(a.difference(b))["bits"] == sorted(sa - sb)
        assert bitmap_to_json(a.xor(b))["bits"] == sorted(sa ^ sb)
        assert a.count() == len(sa)


# ---------------------------------------------------------------------------
# executor vs model over random write sequences
# (reference: TestMain_Set_Quick, server/server_test.go:43-122)
# ---------------------------------------------------------------------------


write_sequences = st.lists(
    st.tuples(
        st.booleans(),  # set vs clear
        st.integers(min_value=0, max_value=5),  # row
        st.integers(min_value=0, max_value=3 * 2**20 - 1),  # column (3 slices)
    ),
    min_size=1,
    max_size=60,
)


class TestExecutorQuick:
    @QUICK
    @given(write_sequences)
    def test_random_writes_match_model(self, tmp_path_factory, seq):
        from pilosa_tpu.core.holder import Holder
        from pilosa_tpu.exec.executor import Executor
        from pilosa_tpu.net.codec import bitmap_to_json
        from pilosa_tpu.pql.parser import parse_string

        holder = Holder(str(tmp_path_factory.mktemp("quick")))
        holder.open()
        idx = holder.create_index("i")
        idx.create_frame("f")
        ex = Executor(holder=holder, host="local")

        model: dict[int, set] = {}
        calls = []
        for is_set, row, col in seq:
            verb = "SetBit" if is_set else "ClearBit"
            calls.append(f'{verb}(frame="f", rowID={row}, columnID={col})')
            if is_set:
                model.setdefault(row, set()).add(col)
            else:
                model.setdefault(row, set()).discard(col)
        ex.execute("i", parse_string(" ".join(calls)))

        for row, want in model.items():
            got = ex.execute(
                "i", parse_string(f'Bitmap(frame="f", rowID={row})')
            )[0]
            assert bitmap_to_json(got)["bits"] == sorted(want)
            n = ex.execute(
                "i", parse_string(f'Count(Bitmap(frame="f", rowID={row}))')
            )[0]
            assert n == len(want)

        # persistence: reopen and re-verify one row
        holder.close()
        holder2 = Holder(holder.path)
        holder2.open()
        ex2 = Executor(holder=holder2, host="local")
        row = max(model)
        got = ex2.execute("i", parse_string(f'Bitmap(frame="f", rowID={row})'))[0]
        assert bitmap_to_json(got)["bits"] == sorted(model[row])
        holder2.close()


# ---------------------------------------------------------------------------
# two-tier fragment storage (sparse-tall, r3)
# ---------------------------------------------------------------------------


fragment_ops = st.lists(
    st.tuples(
        st.sampled_from(["set", "clear", "snapshot-reopen"]),
        st.integers(min_value=0, max_value=30),      # row id
        st.integers(min_value=0, max_value=2**20 - 1),  # column offset
    ),
    min_size=1,
    max_size=60,
)


class TestFragmentTierProperties:
    @QUICK
    @given(ops=fragment_ops, budget=st.integers(min_value=1, max_value=8))
    def test_random_ops_match_set_model(self, ops, budget):
        """Random set/clear/persistence sequences against a tiny dense
        budget behave exactly like a pure-Python set model, regardless
        of which tier each row lands in (the analog of the reference's
        TestMain_Set_Quick, server/server_test.go:43-122)."""
        import pathlib
        import tempfile

        from pilosa_tpu.core.fragment import Fragment

        d = pathlib.Path(tempfile.mkdtemp(prefix="frag-quick-"))
        f = Fragment(
            str(d / "0"), "i", "f", "standard", 0,
            dense_row_budget=budget, max_op_n=10**9,
        )
        f.open()
        model: set[tuple[int, int]] = set()
        try:
            for op, row, col in ops:
                if op == "set":
                    changed = f.set_bit(row, col)
                    assert changed == ((row, col) not in model)
                    model.add((row, col))
                elif op == "clear":
                    changed = f.clear_bit(row, col)
                    assert changed == ((row, col) in model)
                    model.discard((row, col))
                else:
                    f.snapshot()
                    f.close()
                    f = Fragment(
                        str(d / "0"), "i", "f", "standard", 0,
                        dense_row_budget=budget, max_op_n=10**9,
                    )
                    f.open()
                # spot invariants after every op
                assert f.count() == len(model)
            assert sorted(f.for_each_bit()) == sorted(model)
            by_row: dict[int, set[int]] = {}
            for r, c in model:
                by_row.setdefault(r, set()).add(c)
            for r in range(31):
                assert f.row(r).bits() == sorted(by_row.get(r, ())), r
        finally:
            f.close()


# ---------------------------------------------------------------------------
# random query trees (r3): planner fold semantics vs a set oracle
# ---------------------------------------------------------------------------


query_trees = st.recursive(
    st.integers(min_value=0, max_value=6).map(lambda r: ("leaf", r)),
    lambda child: st.tuples(
        st.sampled_from(["Intersect", "Union", "Difference", "Xor"]),
        st.lists(child, min_size=1, max_size=3),
    ),
    max_leaves=6,
)


def _tree_pql(t) -> str:
    if t[0] == "leaf":
        return f'Bitmap(frame="f", rowID={t[1]})'
    return f"{t[0]}({', '.join(_tree_pql(c) for c in t[1])})"


def _tree_oracle(t, rows: dict[int, set]) -> set:
    if t[0] == "leaf":
        return set(rows.get(t[1], set()))
    op, children = t
    sets = [_tree_oracle(c, rows) for c in children]
    acc = sets[0]
    for nxt in sets[1:]:
        if op == "Intersect":
            acc = acc & nxt
        elif op == "Union":
            acc = acc | nxt
        elif op == "Difference":
            acc = acc - nxt
        elif op == "Xor":
            acc = acc ^ nxt
    return acc


class TestQueryTreeProperties:
    """Random nested call trees through the REAL executor (fused
    program + batch cache + host evaluator) vs a Python set oracle —
    hardens the planner fold semantics (exec/plan.py decompose /
    _eval_expr / eval_expr_np) under arbitrary shapes, including
    absent rows (rowID 6 never has bits) and multi-slice rows."""

    @classmethod
    def _holder(cls, tmp_path_factory):
        if not hasattr(cls, "_cached"):
            from pilosa_tpu.core.holder import Holder
            from pilosa_tpu.exec.executor import Executor
            from pilosa_tpu.ops.bitplane import SLICE_WIDTH

            holder = Holder(str(tmp_path_factory.mktemp("trees")))
            holder.open()
            idx = holder.create_index("i")
            f = idx.create_frame("f")
            rng = np.random.default_rng(11)
            rows: dict[int, set] = {}
            for r in range(6):  # row 6 stays absent
                cols = set(
                    int(c)
                    for c in rng.choice(40, size=12, replace=False)
                ) | {int(SLICE_WIDTH + c) for c in rng.choice(20, size=4, replace=False)}
                rows[r] = cols
                for c in cols:
                    f.set_bit("standard", r, c)
            cls._cached = (holder, Executor(holder=holder, host="local"), rows)
        return cls._cached

    @QUICK
    @given(tree=query_trees)
    def test_tree_matches_oracle(self, tmp_path_factory, tree):
        from pilosa_tpu.net.codec import bitmap_to_json
        from pilosa_tpu.pql.parser import parse_string

        holder, ex, rows = self._holder(tmp_path_factory)
        want = _tree_oracle(tree, rows)
        pql = _tree_pql(tree)
        (bm,) = ex.execute("i", parse_string(pql))
        assert bitmap_to_json(bm)["bits"] == sorted(want)
        (n,) = ex.execute("i", parse_string(f"Count({pql})"))
        assert n == len(want)

        # host evaluator parity (the TopN src path) on every slice
        call = parse_string(pql).calls[0]
        host_rows = ex._eval_tree_slices_host("i", call, [0, 1])
        got_bits = set()
        from pilosa_tpu.ops.bitplane import SLICE_WIDTH, np_row_to_columns

        for s, words in host_rows.items():
            if words is None:
                continue
            got_bits |= {
                int(s * SLICE_WIDTH + off) for off in np_row_to_columns(words)
            }
        assert got_bits == want


# ---------------------------------------------------------------------------
# distributed property test: 2 real servers, random writes via alternating
# coordinators (reference: server/server_test.go:43-122 TestMain_Set_Quick,
# strengthened to a real 2-node cluster)
# ---------------------------------------------------------------------------


cluster_write_sequences = st.lists(
    st.tuples(
        st.booleans(),                                   # set / clear
        st.integers(min_value=0, max_value=40),          # row id
        st.integers(min_value=0, max_value=3 * 2**20 - 1),  # col (3 slices)
        st.booleans(),                                   # coordinator 0/1
    ),
    min_size=1,
    max_size=40,
)


@pytest.fixture(scope="session", autouse=True)
def _close_cluster_quick_servers():
    yield
    if TestClusterQuick._servers is not None:
        servers, _ = TestClusterQuick._servers
        for s in servers:
            try:
                s.close()
            except Exception:
                pass
        TestClusterQuick._servers = None


class TestClusterQuick:
    """Random write sequences through BOTH coordinators of a real
    two-node cluster; every row's bits and counts must match a set
    model when queried from EITHER node."""

    _servers = None

    @classmethod
    def _boot(cls, tmp_root):
        from pilosa_tpu.cluster.topology import Cluster
        from pilosa_tpu.net.client import InternalClient
        from pilosa_tpu.net.server import Server

        servers = []
        for i in range(2):
            s = Server(
                data_dir=str(tmp_root / f"cq{i}"),
                cluster=Cluster(replica_n=1),
                anti_entropy_interval=3600,
                polling_interval=3600,
                cache_flush_interval=3600,
            )
            s.open()
            servers.append(s)
        hosts = sorted(s.host for s in servers)
        for s in servers:
            for h in hosts:
                if s.cluster.node_by_host(h) is None:
                    s.cluster.add_node(h)  # add_node keeps the list sorted
        clients = [InternalClient(s.host, timeout=15.0) for s in servers]
        return servers, clients

    @QUICK
    @given(seq=cluster_write_sequences, case=st.integers(0, 10**9))
    def test_random_cluster_writes_match_model(
        self, tmp_path_factory, seq, case
    ):
        if TestClusterQuick._servers is None:
            TestClusterQuick._servers = self._boot(
                tmp_path_factory.mktemp("clusterquick")
            )
        servers, clients = TestClusterQuick._servers
        index = f"q{case}"
        # No broadcaster in this fixture: create the schema on every
        # node directly (the gossip/http broadcast path has its own
        # tests).
        for s in servers:
            s.holder.create_index_if_not_exists(index)
            s.holder.index(index).create_frame_if_not_exists("f")
        try:
            model: dict[int, set] = {}
            for is_set, row, col, coord in seq:
                verb = "SetBit" if is_set else "ClearBit"
                clients[int(coord)].execute_query(
                    index, f'{verb}(frame="f", rowID={row}, columnID={col})'
                )
                if is_set:
                    model.setdefault(row, set()).add(col)
                else:
                    model.setdefault(row, set()).discard(col)
            # max-slice convergence (no broadcaster in this fixture)
            for s in servers:
                s._tick_max_slices()
            from pilosa_tpu.net.codec import bitmap_to_json

            for row, want in model.items():
                for c in clients:
                    n = c.execute_pql(
                        index, f'Count(Bitmap(frame="f", rowID={row}))'
                    )
                    assert n == len(want), (row, n, len(want))
                for c in clients:
                    rb = c.execute_pql(
                        index, f'Bitmap(frame="f", rowID={row})'
                    )
                    assert bitmap_to_json(rb)["bits"] == sorted(want)
        finally:
            for s in servers:
                s.holder.delete_index(index)
