"""Generate golden roaring-format fixtures byte-by-byte from the format
spec (reference: roaring/roaring.go:507-660) — deliberately WITHOUT using
pilosa_tpu.ops.roaring, so the fixtures are an independent oracle for the
codec: a header/offset/op-log deviation in our encoder or decoder cannot
self-validate.

Layout (little-endian):

    u32 cookie = 12346
    u32 containerCount
    containerCount * { u64 key, u32 n-1 }        # key table
    containerCount * { u32 absolute offset }     # payload offsets
    payloads: n <= 4096 -> n sorted u32 low-bits (array form)
              n >  4096 -> 1024 u64 words (bitmap form)
    op-log records until EOF:
        u8 type (0=add 1=remove), u64 value, u32 FNV-1a of first 9 bytes

Run from the repo root:  python tests/golden/make_fixtures.py
Writes *.roaring files plus expected.json (fixture -> sorted set-bit list
after op-log replay) next to this script.
"""

from __future__ import annotations

import json
import os
import struct

HERE = os.path.dirname(os.path.abspath(__file__))
COOKIE = 12346
ARRAY_MAX = 4096


def fnv1a32(data: bytes) -> int:
    h = 0x811C9DC5
    for b in data:
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


def build(containers: list[tuple[int, list[int]]], ops: list[tuple[int, int]] = ()) -> bytes:
    """containers: [(key, sorted low-bit values < 2^16)], keys ascending."""
    header = struct.pack("<II", COOKIE, len(containers))
    keytab = b"".join(
        struct.pack("<QI", key, len(vals) - 1) for key, vals in containers
    )
    payloads = []
    for _, vals in containers:
        assert vals == sorted(set(vals)) and all(0 <= v < 1 << 16 for v in vals)
        if len(vals) <= ARRAY_MAX:
            payloads.append(b"".join(struct.pack("<I", v) for v in vals))
        else:
            words = [0] * 1024
            for v in vals:
                words[v // 64] |= 1 << (v % 64)
            payloads.append(b"".join(struct.pack("<Q", w) for w in words))
    offset = len(header) + len(keytab) + 4 * len(containers)
    offtab = b""
    for p in payloads:
        offtab += struct.pack("<I", offset)
        offset += len(p)
    data = header + keytab + offtab + b"".join(payloads)
    for typ, value in ops:
        rec = struct.pack("<BQ", typ, value)
        data += rec + struct.pack("<I", fnv1a32(rec))
    return data


def replay(containers: list[tuple[int, list[int]]], ops=()) -> list[int]:
    """Expected absolute set bits after op-log replay (spec semantics)."""
    bits = set()
    for key, vals in containers:
        bits.update(key * (1 << 16) + v for v in vals)
    for typ, value in ops:
        if typ == 0:
            bits.add(value)
        else:
            bits.discard(value)
    return sorted(bits)


def main() -> None:
    fixtures: dict[str, tuple[list, list]] = {}

    # array <-> bitmap boundary: exactly 4096 values stays array form;
    # 4097 crosses to the 8 KiB bitmap form (ArrayMaxSize = 4096,
    # reference: roaring/roaring.go:893).
    fixtures["array_boundary_4096"] = ([(0, list(range(0, 8192, 2)))], [])
    fixtures["bitmap_boundary_4097"] = ([(0, list(range(0, 8194, 2)))], [])

    # multi-container: non-contiguous keys spanning multiple slice-rows
    # (16 containers per 2^20-bit row) and mixed array/bitmap forms.
    fixtures["multi_container"] = (
        [
            (0, [0, 1, 65535]),
            (5, [7, 1000]),
            (15, list(range(4097))),        # last container of row 0, bitmap form
            (16, [42]),                     # first container of row 1
            (33, [0]),                      # row 2
            (1 << 30, [123, 456]),          # very high key (row 2^26)
        ],
        [],
    )

    # op-log after snapshot: add to an existing container, add creating a
    # brand-new container, remove an existing bit, remove an absent bit
    # (no-op), re-add a removed bit.
    fixtures["oplog_after_snapshot"] = (
        [(0, [1, 2, 3]), (2, [100])],
        [
            (0, 7),                 # add into key 0
            (0, (5 << 16) + 9),     # add creating key 5
            (1, 2),                 # remove existing
            (1, 999),               # remove absent -> no-op
            (1, (2 << 16) + 100),   # empty out key 2
            (0, 2),                 # re-add previously removed
        ],
    )

    # empty-container dropping: the op-log empties the only container;
    # a correct re-encode of the decoded state writes ZERO containers
    # (the reference skips c.n == 0, roaring/roaring.go:510-531).
    fixtures["oplog_empties_container"] = (
        [(3, [17])],
        [(1, (3 << 16) + 17)],
    )

    # empty file: header only, no containers, no ops.
    fixtures["empty"] = ([], [])

    expected = {}
    for name, (containers, ops) in fixtures.items():
        data = build(containers, ops)
        with open(os.path.join(HERE, name + ".roaring"), "wb") as fh:
            fh.write(data)
        expected[name] = {"bits": replay(containers, ops), "ops": len(ops)}
        print(f"{name}.roaring: {len(data)} bytes, "
              f"{len(expected[name]['bits'])} bits, {len(ops)} ops")

    with open(os.path.join(HERE, "expected.json"), "w") as fh:
        json.dump(expected, fh, indent=1, sort_keys=True)


if __name__ == "__main__":
    main()
