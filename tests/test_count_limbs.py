"""Count-limb math at the on-device reduce boundaries.

TPUs have no int64, so Count() reduces run two-stage in 16-bit limbs of
int32 per-slice-row partials (plan.compiled_total_count): exact for up
to MAX_ONDEVICE_COUNT_PARTIALS (2^15) partials of up to 2^20 bits each.
These tests pin the boundary cases — exactly 2^15 partials, partials at
the 2^20-bit slice maximum, the int32 accumulator budget — plus the
cross-slice merge's duplicate-id semantics.
"""

import numpy as np

from pilosa_tpu.exec import plan
from pilosa_tpu.exec.executor import merge_counts_by_id

LEAF = ("leaf", 0)


def test_recombine_scalar():
    assert plan.recombine_count_limbs(np.array([0, 0])) == 0
    assert plan.recombine_count_limbs(np.array([0, 123])) == 123
    assert plan.recombine_count_limbs(np.array([3, 5])) == (3 << 16) + 5
    out = plan.recombine_count_limbs(np.array([1, 0]))
    assert isinstance(out, int) and out == 1 << 16


def test_recombine_vector():
    limbs = np.array([[0, 1, 16], [7, 0xFFFF, 0]])
    out = plan.recombine_count_limbs(limbs)
    assert out.dtype == np.int64
    np.testing.assert_array_equal(
        out, [7, (1 << 16) + 0xFFFF, 16 << 16]
    )


def test_total_count_exactly_max_partials():
    """Exactly 2^15 partials — the documented budget edge — through the
    real two-stage limb program (small word count keeps it cheap; the
    limb math is word-count independent)."""
    n = plan.MAX_ONDEVICE_COUNT_PARTIALS
    words = 4
    batch = np.full((n, 1, words), 0xFFFFFFFF, dtype=np.uint32)
    limbs = plan.compiled_total_count(LEAF)(batch)
    assert plan.recombine_count_limbs(np.asarray(limbs)) == n * words * 32


def test_total_count_partials_at_slice_max():
    """Partials at the 2^20-bit slice-row maximum: all-ones full-width
    rows, where the lo limb of each partial is exactly 0 and the total
    rides entirely on the hi limb."""
    from pilosa_tpu.ops import bitplane as bp

    n = 8
    batch = np.full((n, 1, bp.WORDS_PER_SLICE), 0xFFFFFFFF, dtype=np.uint32)
    limbs = np.asarray(plan.compiled_total_count(LEAF)(batch))
    assert limbs[1] == 0  # (2^20 & 0xFFFF) == 0 per partial
    assert plan.recombine_count_limbs(limbs) == n * (1 << 20)
    # The batched per-slice fallback agrees (the path callers take past
    # the partial budget).
    per = np.asarray(plan.compiled_batched(LEAF, "count")(batch))
    assert int(per.astype(np.int64).sum()) == n * (1 << 20)


def test_limb_budget_int32_exact_at_boundary():
    """The worst-case accumulator load inside the budget: 2^15 partials
    of 2^20 - 1 bits (lo limb 0xFFFF each) must stay below the int32
    ceiling in BOTH limb sums, and recombine exactly."""
    n = plan.MAX_ONDEVICE_COUNT_PARTIALS
    partials = np.full(n, (1 << 20) - 1, dtype=np.int64)
    lo = int(np.sum(partials & 0xFFFF))
    hi = int(np.sum(partials >> 16))
    i32max = np.iinfo(np.int32).max
    assert lo <= i32max and hi <= i32max
    assert plan.recombine_count_limbs(np.array([hi, lo])) == int(
        partials.sum()
    )


def test_two_stage_matches_flat_sum_random(rng):
    """Random partial mix: limb-split + recombine == the flat int64 sum
    (the invariant the device program relies on)."""
    partials = rng.integers(0, 1 << 20, size=4096).astype(np.int64)
    lo = int(np.sum(partials & 0xFFFF))
    hi = int(np.sum(partials >> 16))
    assert plan.recombine_count_limbs(np.array([hi, lo])) == int(
        partials.sum()
    )


def test_merge_counts_by_id_duplicates_across_slices():
    parts = [
        (np.array([1, 2, 3], np.int64), np.array([10, 20, 30], np.int64)),
        (np.array([2, 3, 4], np.int64), np.array([5, 5, 5], np.int64)),
        (np.array([], np.int64), np.array([], np.int64)),
        (np.array([1], np.int64), np.array([1], np.int64)),
    ]
    uids, sums = merge_counts_by_id(parts)
    np.testing.assert_array_equal(uids, [1, 2, 3, 4])
    np.testing.assert_array_equal(sums, [11, 25, 35, 5])


def test_merge_counts_by_id_empty():
    assert merge_counts_by_id([]) is None
    assert (
        merge_counts_by_id([(np.array([], np.int64), np.array([], np.int64))])
        is None
    )
