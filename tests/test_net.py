"""HTTP API layer: codec roundtrips, handler routes, client, servers.

Mirrors the reference's handler/server test strategy (reference:
handler_test.go, server/server_test.go): full-process servers bound to
port 0 in one process, exercised through the real client.
"""

import io
import json
import time

import pytest

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.topology import Cluster, Node
from pilosa_tpu.core.bitmap import RowBitmap
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.net import codec
from pilosa_tpu.net import wire_pb2 as wire
from pilosa_tpu.net.client import ClientError, InternalClient
from pilosa_tpu.net.handler import Handler, Request
from pilosa_tpu.net.server import Server
from pilosa_tpu.ops.bitplane import SLICE_WIDTH


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


class TestCodec:
    def test_attrs_roundtrip(self):
        attrs = {"s": "hi", "i": 42, "b": True, "f": 1.5}
        back = codec.attrs_from_proto(codec.attrs_to_proto(attrs))
        assert back == attrs

    def test_attrs_sorted_by_key(self):
        pb = codec.attrs_to_proto({"z": 1, "a": 2})
        assert [a.Key for a in pb] == ["a", "z"]

    def test_bitmap_roundtrip(self):
        b = RowBitmap.from_bits([1, 66000, SLICE_WIDTH + 5])
        pb = codec.bitmap_to_proto(b)
        assert list(pb.Bits) == [1, 66000, SLICE_WIDTH + 5]
        back = codec.bitmap_from_proto(pb)
        assert codec.bitmap_to_json(back)["bits"] == [1, 66000, SLICE_WIDTH + 5]

    def test_result_polymorphism(self):
        # count
        assert codec.result_from_proto(codec.result_to_proto(7)) == 7
        # changed flag
        assert codec.result_from_proto(codec.result_to_proto(True)) is True
        # pairs
        pairs = codec.result_from_proto(
            codec.result_to_proto([Pair(id=3, count=9)])
        )
        assert [(p.id, p.count) for p in pairs] == [(3, 9)]
        # bitmap
        rb = codec.result_from_proto(
            codec.result_to_proto(RowBitmap.from_bits([10]))
        )
        assert isinstance(rb, RowBitmap)

    def test_response_json_shape(self):
        out = codec.response_to_json([5, RowBitmap.from_bits([1])])
        assert out["results"][0] == 5
        assert out["results"][1] == {"attrs": {}, "bits": [1]}


# ---------------------------------------------------------------------------
# broadcast envelope
# ---------------------------------------------------------------------------


class TestBroadcastEnvelope:
    @pytest.mark.parametrize(
        "msg",
        [
            wire.CreateSliceMessage(Index="i", Slice=3, IsInverse=True),
            wire.CreateIndexMessage(
                Index="i", Meta=wire.IndexMeta(ColumnLabel="col")
            ),
            wire.DeleteIndexMessage(Index="i"),
            wire.CreateFrameMessage(
                Index="i", Frame="f", Meta=wire.FrameMeta(RowLabel="row")
            ),
            wire.DeleteFrameMessage(Index="i", Frame="f"),
        ],
    )
    def test_roundtrip(self, msg):
        back = bc.unmarshal_message(bc.marshal_message(msg))
        assert type(back) is type(msg)
        assert back.SerializeToString() == msg.SerializeToString()

    def test_unknown_type(self):
        with pytest.raises(ValueError):
            bc.unmarshal_message(b"\xff\x00")


# ---------------------------------------------------------------------------
# single-node server over HTTP
# ---------------------------------------------------------------------------


@pytest.fixture
def server(tmp_path):
    s = Server(
        data_dir=str(tmp_path / "data"),
        host="127.0.0.1:0",
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
    )
    s.open()
    yield s
    s.close()


@pytest.fixture
def client(server):
    return InternalClient(server.host, timeout=10.0)


class TestServerHTTP:
    def test_version(self, server, client):
        status, data = client._request("GET", "/version")
        assert status == 200
        assert "version" in json.loads(data)

    def test_index_frame_crud(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f", {"rowLabel": "rid"})
        schema = client.schema()
        assert schema[0]["name"] == "i"
        assert schema[0]["frames"][0]["name"] == "f"
        # conflict
        with pytest.raises(ClientError):
            client.create_index("i")
        with pytest.raises(ClientError):
            client.create_frame("i", "f")
        client.delete_index("i")
        assert client.schema() == []

    def test_query_json(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f")
        status, data = client._request(
            "POST",
            "/index/i/query",
            body=b'SetBit(frame="f", rowID=1, columnID=5)',
        )
        assert status == 200
        assert json.loads(data)["results"] == [True]
        status, data = client._request(
            "POST", "/index/i/query", body=b'Count(Bitmap(frame="f", rowID=1))'
        )
        assert json.loads(data)["results"] == [1]
        status, data = client._request(
            "POST", "/index/i/query", body=b'Bitmap(frame="f", rowID=1)'
        )
        assert json.loads(data)["results"] == [{"attrs": {}, "bits": [5]}]

    def test_query_protobuf(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f")
        client.execute_query("i", 'SetBit(frame="f", rowID=2, columnID=9)')
        assert client.execute_pql("i", 'Count(Bitmap(frame="f", rowID=2))') == 1
        rb = client.execute_pql("i", 'Bitmap(frame="f", rowID=2)')
        assert isinstance(rb, RowBitmap)
        assert codec.bitmap_to_json(rb)["bits"] == [9]

    def test_query_error_status(self, server, client):
        client.create_index("i")
        status, data = client._request(
            "POST", "/index/i/query", body=b"Bitmap("
        )
        assert status == 400
        assert "error" in json.loads(data)

    def test_query_slices_url_arg(self, server, client):
        """?slices=0,2 restricts execution to the named slices
        (reference: handler_test.go TestHandler_Query_Args_URL)."""
        client.create_index("i")
        client.create_frame("i", "f")
        for s in range(3):
            client.execute_query(
                "i", f'SetBit(frame="f", rowID=1, columnID={s * SLICE_WIDTH})'
            )
        status, data = client._request(
            "POST",
            "/index/i/query",
            query={"slices": "0,2"},
            body=b'Count(Bitmap(frame="f", rowID=1))',
        )
        assert status == 200
        assert json.loads(data)["results"] == [2]

    def test_query_invalid_params(self, server, client):
        client.create_index("i")
        status, _ = client._request(
            "POST", "/index/i/query", query={"bogus": "1"}, body=b"Count()"
        )
        assert status == 400

    def test_query_time_granularity_validated(self, server, client):
        """``time_granularity`` is validated like the reference
        (handler.go:913-919: invalid -> 400 "invalid time granularity")
        and — also like the reference — has no effect on execution:
        Range() always uses the frame's own quantum (reference:
        executor.go:572-573; QueryRequest.Quantum is never consumed)."""
        client.create_index("i")
        client.create_frame("i", "f", {"timeQuantum": "YMD"})
        status, data = client._request(
            "POST",
            "/index/i/query",
            query={"time_granularity": "XQ"},
            body=b'Count(Bitmap(frame="f", rowID=1))',
        )
        assert status == 400
        assert "granularity" in json.loads(data)["error"]
        client._request(
            "POST",
            "/index/i/query",
            body=b'SetBit(frame="f", rowID=1, columnID=2,'
            b' timestamp="2017-03-20T10:30")',
        )
        q = (
            b'Range(frame="f", rowID=1, start="2017-03-19T00:00",'
            b' end="2017-03-22T00:00")'
        )
        expected = [{"attrs": {}, "bits": [2]}]
        # a VALID granularity is accepted -- and changes nothing
        for extra in ({}, {"time_granularity": "Y"}):
            status, data = client._request(
                "POST", "/index/i/query", query=extra, body=q
            )
            assert status == 200
            assert json.loads(data)["results"] == expected

    def test_column_attrs_on_query(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f")
        client.execute_query("i", 'SetBit(frame="f", rowID=1, columnID=3)')
        client.execute_query("i", 'SetColumnAttrs(id=3, name="c3")')
        status, data = client._request(
            "POST",
            "/index/i/query",
            query={"columnAttrs": "true"},
            body=b'Bitmap(frame="f", rowID=1)',
        )
        out = json.loads(data)
        assert out["columnAttrs"] == [{"id": 3, "attrs": {"name": "c3"}}]

    def test_slice_max(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f")
        client.execute_query(
            "i", f'SetBit(frame="f", rowID=0, columnID={SLICE_WIDTH * 2 + 1})'
        )
        assert client.max_slice_by_index() == {"i": 2}

    def test_slice_max_inverse(self, server, client):
        """/slices/max?inverse=true reports the INVERSE slice space
        (sliced by rowID — reference: handler_test.go
        TestHandler_MaxSlices_Inverse)."""
        client.create_index("i")
        client.create_frame("i", "f", {"inverseEnabled": True})
        client.execute_query(
            "i",
            f'SetBit(frame="f", rowID={SLICE_WIDTH * 3 + 7}, columnID=1)',
        )
        assert client.max_slice_by_index() == {"i": 0}
        assert client.max_slice_by_index(inverse=True) == {"i": 3}

    def test_import_and_export(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f")
        bits = [(0, 1), (0, 2), (3, 4)]
        client.import_bits("i", "f", 0, bits)
        assert client.execute_pql("i", 'Count(Bitmap(frame="f", rowID=0))') == 2
        csv = client.export_csv("i", "f", "standard", 0)
        rows = sorted(
            tuple(map(int, line.split(","))) for line in csv.strip().splitlines()
        )
        assert rows == [(0, 1), (0, 2), (3, 4)]

    def test_fragment_nodes(self, server, client):
        client.create_index("i")
        nodes = client.fragment_nodes("i", 0)
        assert nodes[0]["host"] == server.host

    def test_backup_restore_inverse_view(self, server, client):
        """Backup/restore of a derived (inverse) view round-trips its
        bits; the max-slice lookup must use the INVERSE slice space
        (reference: client_test.go TestClient_BackupInverseView)."""
        client.create_index("i")
        client.create_frame("i", "f", {"inverseEnabled": True})
        # rowID >= SLICE_WIDTH: the INVERSE view's slice space (sliced
        # by rowID) reaches slice 1 while the standard space stays at
        # slice 0 — a wrong (standard) max-slice lookup would silently
        # drop this bit from the archive.
        row = SLICE_WIDTH + 5
        client.execute_query("i", f'SetBit(frame="f", rowID={row}, columnID=9)')
        buf = io.BytesIO()
        client.backup_to(buf, "i", "f", "inverse")
        # clear and restore
        frag = server.holder.fragment("i", "f", "inverse", 1)
        assert frag.row(9).count() == 1
        frag.clear_bit(9, row)
        assert frag.row(9).count() == 0
        buf.seek(0)
        client.restore_from(buf, "i", "f", "inverse")
        frag = server.holder.fragment("i", "f", "inverse", 1)
        assert frag.row(9).bits() == [row]

    def test_backup_invalid_view_errors(self, server, client):
        """Backing up a nonexistent view must error, not return an
        empty archive (reference: client_test.go
        TestClient_BackupInvalidView)."""
        client.create_index("i")
        client.create_frame("i", "f")
        client.execute_query("i", 'SetBit(frame="f", rowID=1, columnID=1)')
        with pytest.raises(ClientError):
            client.backup_to(io.BytesIO(), "i", "f", "no_such_view")

    def test_import_not_owned_rejected_412(self, server, client):
        """A node must refuse an /import for a slice it does not own
        (reference: handler.go:1004 OwnsFragment guard -> 412) — the
        cluster here claims a second host owning odd slices."""
        import urllib.request

        client.create_index("i")
        client.create_frame("i", "f")
        # Rewire the server's cluster so SOME slice maps elsewhere.
        two = Cluster(nodes=[Node(host=server.host), Node(host="other:1")])
        server.cluster.nodes = two.nodes
        try:
            bad = None
            for s in range(64):
                owners = [n.host for n in server.cluster.fragment_nodes("i", s)]
                if server.host not in owners:
                    bad = s
                    break
            assert bad is not None
            pb = wire.ImportRequest(Index="i", Frame="f", Slice=bad)
            pb.RowIDs.append(0)
            pb.ColumnIDs.append(bad << 20)
            body = pb.SerializeToString()
            req = urllib.request.Request(
                f"http://{server.host}/import",
                data=body,
                method="POST",
                headers={"Content-Type": "application/x-protobuf"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 412
        finally:
            server.cluster.nodes = [Node(host=server.host)]

    def test_fragment_backup_restore(self, server, client, tmp_path):
        client.create_index("i")
        client.create_frame("i", "f")
        client.execute_query("i", 'SetBit(frame="f", rowID=7, columnID=8)')
        data = client.backup_slice("i", "f", "standard", 0)
        assert data is not None
        # wipe and restore
        client.delete_index("i")
        client.create_index("i")
        client.create_frame("i", "f")
        client.restore_slice("i", "f", "standard", 0, data)
        assert client.execute_pql("i", 'Count(Bitmap(frame="f", rowID=7))') == 1

    def test_backup_to_restore_from(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f")
        for col in (1, SLICE_WIDTH + 2):
            client.execute_query("i", f'SetBit(frame="f", rowID=1, columnID={col})')
        buf = io.BytesIO()
        client.backup_to(buf, "i", "f", "standard")
        client.delete_index("i")
        client.create_index("i")
        client.create_frame("i", "f")
        buf.seek(0)
        client.restore_from(buf, "i", "f", "standard")
        got = client.execute_pql("i", 'Bitmap(frame="f", rowID=1)')
        assert codec.bitmap_to_json(got)["bits"] == [1, SLICE_WIDTH + 2]

    def test_fragment_blocks_and_block_data(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f")
        client.execute_query("i", 'SetBit(frame="f", rowID=1, columnID=5)')
        blocks = client.fragment_blocks("i", "f", "standard", 0)
        assert len(blocks) == 1 and blocks[0][0] == 0
        rows, cols = client.block_data("i", "f", "standard", 0, 0)
        assert rows == [1] and cols == [5]

    def test_attr_diff(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f")
        client.execute_query("i", 'SetColumnAttrs(id=1, color="red")')
        client.execute_query("i", 'SetRowAttrs(frame="f", rowID=2, tag="x")')
        # empty remote blocks -> everything differs
        assert client.column_attr_diff("i", []) == {1: {"color": "red"}}
        assert client.row_attr_diff("i", "f", []) == {2: {"tag": "x"}}
        # matching blocks -> no diff
        local = server.holder.index("i").column_attr_store.blocks()
        assert client.column_attr_diff("i", local) == {}

    def test_views_and_time_quantum(self, server, client):
        client.create_index("i")
        client.create_frame("i", "f")
        status, _ = client._request(
            "PATCH",
            "/index/i/frame/f/time-quantum",
            body=json.dumps({"timeQuantum": "YM"}).encode(),
        )
        assert status == 200
        client.execute_query(
            "i",
            'SetBit(frame="f", rowID=1, columnID=2, timestamp="2024-03-05T10:00")',
        )
        views = client.frame_views("i", "f")
        assert "standard" in views
        assert "standard_2024" in views and "standard_202403" in views

    def test_status_hosts(self, server, client):
        status, data = client._request("GET", "/status")
        assert json.loads(data)["status"]["Nodes"][0]["Host"] == server.host
        status, data = client._request("GET", "/hosts")
        assert json.loads(data)[0]["host"] == server.host

    def test_webui(self, server, client):
        status, data = client._request("GET", "/")
        assert status == 200 and b"pilosa-tpu" in data
        # The console shell carries the three interface areas the
        # reference console has: REPL, index dropdown, cluster pane.
        for marker in (b'id="query"', b'id="index-dropdown"', b'id="pane-cluster"'):
            assert marker in data, marker
        status, data = client._request("GET", "/assets/main.js")
        assert status == 200
        # Feature markers: REPL history, tab completion, meta commands,
        # cluster rendering (reference: webui/assets/main.js).
        for marker in (b"class Repl", b"completeAtCursor", b"parseMeta",
                       b"refreshCluster"):
            assert marker in data, marker
        status, data = client._request("GET", "/assets/main.css")
        assert status == 200
        status, _ = client._request("GET", "/assets/nope.js")
        assert status == 404

    def test_debug_endpoints(self, server, client):
        status, data = client._request("GET", "/debug/vars")
        assert status == 200 and "uptime_seconds" in json.loads(data)
        status, data = client._request("GET", "/debug/pprof/")
        assert status == 200 and b"thread" in data

    def test_pprof_profile_and_heap(self, server, client):
        """CPU-profile + heap endpoints (the reference mounts full
        net/http/pprof, handler.go:111-112)."""
        # CPU: a short sample window still catches the server's own
        # threads (rx loops sleeping in poll etc.) as folded stacks.
        status, data = client._request(
            "GET", "/debug/pprof/profile", query={"seconds": "0.3"}
        )
        assert status == 200
        text = data.decode()
        assert text.strip(), "no samples collected"
        line = text.strip().splitlines()[0]
        stack, _, count = line.rpartition(" ")
        assert stack and count.isdigit()
        # heap: start -> snapshot -> stop
        status, data = client._request(
            "GET", "/debug/pprof/heap", query={"start": "1"}
        )
        assert status == 200 and b"started" in data
        client._request("GET", "/schema")  # allocate something traced
        status, data = client._request("GET", "/debug/pprof/heap")
        assert status == 200 and b".py" in data
        status, data = client._request(
            "GET", "/debug/pprof/heap", query={"stop": "1"}
        )
        assert status == 200 and b"stopped" in data
        status, _ = client._request("GET", "/debug/pprof/bogus")
        assert status == 404

    def test_not_found_route(self, server, client):
        status, _ = client._request("GET", "/nope")
        assert status == 404


# ---------------------------------------------------------------------------
# multi-node: two real servers, one cluster
# ---------------------------------------------------------------------------


@pytest.fixture
def two_servers(tmp_path):
    # Real http broadcast between the nodes (reference cluster.type=http):
    # receivers bind at open; broadcaster host lists are filled once both
    # ports are known.
    recv0, recv1 = bc.HTTPBroadcastReceiver(), bc.HTTPBroadcastReceiver()
    b0, b1 = bc.HTTPBroadcaster([]), bc.HTTPBroadcaster([])
    cluster0 = Cluster(replica_n=1)
    cluster1 = Cluster(replica_n=1)
    s0 = Server(
        data_dir=str(tmp_path / "n0"),
        cluster=cluster0,
        broadcaster=b0,
        broadcast_receiver=recv0,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
    )
    s1 = Server(
        data_dir=str(tmp_path / "n1"),
        cluster=cluster1,
        broadcaster=b1,
        broadcast_receiver=recv1,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
    )
    s0.open()
    s1.open()
    b0.internal_hosts.append(recv1.bound_host)
    b1.internal_hosts.append(recv0.bound_host)
    # Both clusters know both nodes, in the same order (hash-identical
    # placement requires identical node lists).
    for c in (cluster0, cluster1):
        for host in sorted([s0.host, s1.host]):
            if c.node_by_host(host) is None:
                c.add_node(host)
    # nodes list order must match across clusters
    cluster0.nodes.sort(key=lambda n: n.host)
    cluster1.nodes.sort(key=lambda n: n.host)
    yield s0, s1
    s0.close()
    s1.close()


class TestMultiNode:
    def _setup_schema(self, servers):
        for s in servers:
            s.holder.create_index_if_not_exists("i")
            s.holder.index("i").create_frame_if_not_exists("f")

    def test_query_fans_out(self, two_servers):
        s0, s1 = two_servers
        self._setup_schema(two_servers)
        c0 = InternalClient(s0.host, timeout=10.0)
        # Write bits across many slices; writes route to the owning node
        # through the coordinator.
        cols = [1, SLICE_WIDTH + 2, 2 * SLICE_WIDTH + 3, 5 * SLICE_WIDTH + 4]
        for col in cols:
            c0.execute_query("i", f'SetBit(frame="f", rowID=1, columnID={col})')
        # The CreateSliceMessage broadcast is async; a coordinator only
        # counts slices it has learned about — wait for BOTH nodes to
        # know the cluster max slice before asserting counts.
        c1 = InternalClient(s1.host, timeout=10.0)
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if (
                s0.holder.index("i").max_slice() == 5
                and s1.holder.index("i").max_slice() == 5
            ):
                break
            time.sleep(0.02)
        # Count from either coordinator sees all slices.
        assert c0.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == 4
        assert c1.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == 4
        rb = c1.execute_pql("i", 'Bitmap(frame="f", rowID=1)')
        assert codec.bitmap_to_json(rb)["bits"] == sorted(cols)

    def test_bits_actually_distributed(self, two_servers):
        s0, s1 = two_servers
        self._setup_schema(two_servers)
        c0 = InternalClient(s0.host, timeout=10.0)
        for sl in range(6):
            c0.execute_query(
                "i", f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH})'
            )

        def local_count(server):
            total = 0
            for sl in range(6):
                frag = server.holder.fragment("i", "f", "standard", sl)
                if frag is not None:
                    total += frag.count()
            return total

        # Each node holds only its owned slices; together they hold all.
        assert local_count(s0) + local_count(s1) == 6
        assert 0 < local_count(s0) < 6

    def test_replica_write_fanout(self, tmp_path):
        cluster0 = Cluster(replica_n=2)
        cluster1 = Cluster(replica_n=2)
        s0 = Server(
            data_dir=str(tmp_path / "r0"), cluster=cluster0,
            anti_entropy_interval=3600, polling_interval=3600,
            cache_flush_interval=3600,
        )
        s1 = Server(
            data_dir=str(tmp_path / "r1"), cluster=cluster1,
            anti_entropy_interval=3600, polling_interval=3600,
            cache_flush_interval=3600,
        )
        s0.open()
        s1.open()
        try:
            for c in (cluster0, cluster1):
                for host in sorted([s0.host, s1.host]):
                    if c.node_by_host(host) is None:
                        c.add_node(host)
                c.nodes.sort(key=lambda n: n.host)
            for s in (s0, s1):
                s.holder.create_index_if_not_exists("i")
                s.holder.index("i").create_frame_if_not_exists("f")
            c0 = InternalClient(s0.host, timeout=10.0)
            c0.execute_query("i", 'SetBit(frame="f", rowID=1, columnID=5)')
            # With replica_n=2 and 2 nodes, both hold the bit.
            for s in (s0, s1):
                frag = s.holder.fragment("i", "f", "standard", 0)
                assert frag is not None and frag.contains(1, 5)
            # Timestamped writes fan out too, landing in time views on
            # EVERY replica (reference: executor_test.go
            # TestExecutor_Execute_Remote_SetBit_With_Timestamp).
            for s in (s0, s1):
                s.holder.frame("i", "f").set_time_quantum("Y")
            c0.execute_query(
                "i",
                'SetBit(frame="f", rowID=7, columnID=3,'
                ' timestamp="2019-06-01T00:00")',
            )
            for s in (s0, s1):
                tf = s.holder.fragment("i", "f", "standard_2019", 0)
                assert tf is not None and tf.contains(7, 3), s.host
        finally:
            s0.close()
            s1.close()

    def test_remote_import_routes_to_owner(self, two_servers):
        s0, s1 = two_servers
        self._setup_schema(two_servers)
        c0 = InternalClient(s0.host, timeout=10.0)
        # import into slices 0..5 via node 0 only; client routes each
        # slice to its owner.
        for sl in range(6):
            c0.import_bits("i", "f", sl, [(2, sl * SLICE_WIDTH + 1)])
        assert c0.execute_pql("i", 'Count(Bitmap(frame="f", rowID=2))') == 6

    def test_import_fanout_dead_replica_names_node_and_converges(
        self, tmp_path
    ):
        """Import fan-out with one replica hard-down: the error names
        the FAILED node (and only it), the surviving replica holds the
        bits consistently, and re-running the import after the node
        recovers converges every replica (set-bit imports are
        idempotent)."""
        def make(name, host="127.0.0.1:0"):
            cluster = Cluster(replica_n=2)
            s = Server(
                data_dir=str(tmp_path / name), host=host, cluster=cluster,
                anti_entropy_interval=3600, polling_interval=3600,
                cache_flush_interval=3600,
            )
            s.open()
            return s

        def join(*servers):
            for s in servers:
                for host in sorted(x.host for x in servers):
                    if s.cluster.node_by_host(host) is None:
                        s.cluster.add_node(host)
                s.cluster.nodes.sort(key=lambda n: n.host)
                s.holder.create_index_if_not_exists("i")
                s.holder.index("i").create_frame_if_not_exists("f")

        s0 = make("r0")
        s1 = make("r1")
        s1b = None
        try:
            join(s0, s1)
            c0 = InternalClient(s0.host, timeout=5.0)
            bits = [(3, 1), (3, SLICE_WIDTH - 2)]
            dead_host = s1.host
            s1.close()  # replica_n=2: slice 0 still has a live owner

            with pytest.raises(ClientError) as ei:
                c0.import_bits("i", "f", 0, bits)
            # The error names the failed node and ONLY the failed node.
            assert dead_host in str(ei.value)
            assert s0.host not in str(ei.value)
            # The surviving replica applied the import consistently.
            frag = s0.holder.fragment("i", "f", "standard", 0)
            assert frag is not None
            assert frag.contains(3, 1) and frag.contains(3, SLICE_WIDTH - 2)

            # Recovery: the node comes back on the same host/data_dir;
            # a retried import converges all replicas.
            s1b = make("r1", host=dead_host)
            join(s0, s1b)
            c0.import_bits("i", "f", 0, bits)
            for s in (s0, s1b):
                frag = s.holder.fragment("i", "f", "standard", 0)
                assert frag is not None, s.host
                assert frag.contains(3, 1), s.host
                assert frag.contains(3, SLICE_WIDTH - 2), s.host
        finally:
            s0.close()
            if s1b is not None:
                s1b.close()

    def test_topn_two_phase_across_nodes(self, two_servers):
        """Distributed two-phase TopN: phase 1 trims to each slice's
        local top-n, so a row that ranks 3rd on every slice but 2nd
        globally is undercounted until the phase-2 ids refetch
        (reference: executor.go:281-321).  The final counts must be
        exact from EITHER coordinator."""
        s0, s1 = two_servers
        self._setup_schema(two_servers)
        c0 = InternalClient(s0.host, timeout=10.0)
        c1 = InternalClient(s1.host, timeout=10.0)

        # src row 0: cols 0..19 of both slices.
        # slice 0: row1 overlaps 10, row2 9, row3 8
        # slice 1: row4 overlaps 10, row3 9, row5 8
        # => globally row3 = 17, beaten only by row0 (self, 40).
        bits = []
        for base in (0, SLICE_WIDTH):
            bits += [(0, base + c) for c in range(20)]
        bits += [(1, c) for c in range(10)]
        bits += [(2, c) for c in range(9)]
        bits += [(3, c) for c in range(8)]
        bits += [(4, SLICE_WIDTH + c) for c in range(10)]
        bits += [(3, SLICE_WIDTH + c) for c in range(9)]
        bits += [(5, SLICE_WIDTH + c) for c in range(8)]
        for row, col in bits:
            c0.execute_query(
                "i", f'SetBit(frame="f", rowID={row}, columnID={col})'
            )
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if (
                s0.holder.index("i").max_slice() == 1
                and s1.holder.index("i").max_slice() == 1
            ):
                break
            time.sleep(0.02)

        want = [
            {"id": 0, "count": 40},
            {"id": 3, "count": 17},
            {"id": 1, "count": 10},
        ]
        for c in (c0, c1):
            got = c.execute_pql(
                "i", 'TopN(Bitmap(frame="f", rowID=0), frame="f", n=3)'
            )
            got = [{"id": p.id, "count": p.count} for p in got]
            assert got == want, got


# ---------------------------------------------------------------------------
# http broadcast between two servers
# ---------------------------------------------------------------------------
class TestHTTPBroadcast:
    def test_schema_replicates(self, tmp_path):
        recv1 = bc.HTTPBroadcastReceiver()
        s1 = Server(
            data_dir=str(tmp_path / "b1"),
            broadcast_receiver=recv1,
            anti_entropy_interval=3600, polling_interval=3600,
            cache_flush_interval=3600,
        )
        s1.open()
        try:
            broadcaster = bc.HTTPBroadcaster([recv1.bound_host])
            s0 = Server(
                data_dir=str(tmp_path / "b0"),
                broadcaster=broadcaster,
                anti_entropy_interval=3600, polling_interval=3600,
                cache_flush_interval=3600,
            )
            s0.open()
            try:
                c0 = InternalClient(s0.host, timeout=10.0)
                c0.create_index("i", {"columnLabel": "col"})
                c0.create_frame("i", "f", {"rowLabel": "row"})
                # replicated to s1 through the internal listener
                idx = s1.holder.index("i")
                assert idx is not None and idx.column_label == "col"
                assert idx.frame("f").row_label == "row"
                c0.delete_index("i")
                assert s1.holder.index("i") is None
            finally:
                s0.close()
        finally:
            s1.close()


# ---------------------------------------------------------------------------
# anti-entropy over live servers
# ---------------------------------------------------------------------------


class TestAntiEntropy:
    def test_fragment_sync_converges(self, two_servers):
        from pilosa_tpu.sync.syncer import HolderSyncer

        s0, s1 = two_servers
        self._diverge(s0, s1)
        # Run the syncer from each node; replicas converge to majority.
        HolderSyncer(
            holder=s0.holder, host=s0.host, cluster=s0.cluster
        ).sync_holder()
        HolderSyncer(
            holder=s1.holder, host=s1.host, cluster=s1.cluster
        ).sync_holder()
        c = InternalClient(s0.host, timeout=10.0)
        n = c.execute_pql("i", 'Count(Bitmap(frame="f", rowID=0))')
        assert n == 3

    def _diverge(self, s0, s1):
        # replica_n=1: each slice owned by exactly one node; write bits
        # directly into one node's fragment for a slice the *other* node
        # owns, so sync must repair it.
        for s in (s0, s1):
            s.holder.create_index_if_not_exists("i")
            s.holder.index("i").create_frame_if_not_exists("f")
        # find a slice owned by s0
        owned_by_0 = next(
            sl for sl in range(8) if s0.cluster.owns_fragment(s0.host, "i", sl)
        )
        # write the authoritative copy on the owner
        c0 = InternalClient(s0.host, timeout=10.0)
        base = owned_by_0 * SLICE_WIDTH
        for col in (base + 1, base + 2, base + 3):
            c0.execute_query("i", f'SetBit(frame="f", rowID=0, columnID={col})')

    def test_inverse_view_divergence_converges(self, tmp_path):
        """Divergence introduced DIRECTLY in a derived (inverse) view —
        e.g. a partial import on one replica — is detected and repaired
        through the view-scoped sync path, something standard-only block
        sync can never see (the reference walks every view,
        holder.go:524-556, but only merges standard data,
        fragment.go:1443)."""
        from pilosa_tpu.sync.syncer import HolderSyncer

        clusters = [Cluster(replica_n=2) for _ in range(2)]
        servers = [
            Server(
                data_dir=str(tmp_path / f"r{i}"),
                cluster=clusters[i],
                anti_entropy_interval=3600,
                polling_interval=3600,
                cache_flush_interval=3600,
            )
            for i in range(2)
        ]
        for s in servers:
            s.open()
        try:
            hosts = sorted(s.host for s in servers)
            for c in clusters:
                for h in hosts:
                    if c.node_by_host(h) is None:
                        c.add_node(h)
                c.nodes.sort(key=lambda n: n.host)
            s0, s1 = servers
            for s in servers:
                s.holder.create_index_if_not_exists("i")
                s.holder.index("i").create_frame_if_not_exists(
                    "f", inverse_enabled=True
                )
            # Identical data on both replicas through the write fan-out.
            c0 = InternalClient(s0.host, timeout=10.0)
            c0.execute_query("i", 'SetBit(frame="f", rowID=3, columnID=9)')
            # Diverge ONLY s1's inverse view: direct fragment write that
            # no broadcast or standard-view checksum can observe.
            frag1 = s1.holder.fragment("i", "f", "inverse", 0)
            assert frag1 is not None
            frag1.set_bit(42, 7)
            # ...and s0's, in the other direction — this one must be
            # PUSHED to s1 over the view-scoped import endpoint.
            frag0 = s0.holder.fragment("i", "f", "inverse", 0)
            frag0.set_bit(43, 8)
            # Standard views still agree everywhere.
            std0 = dict(s0.holder.fragment("i", "f", "standard", 0).blocks())
            std1 = dict(s1.holder.fragment("i", "f", "standard", 0).blocks())
            assert std0 == std1
            # Anti-entropy from s0 pulls the diverged inverse bit.
            HolderSyncer(
                holder=s0.holder, host=s0.host, cluster=clusters[0]
            ).sync_holder()
            assert frag0.contains(42, 7)  # pulled from s1
            assert frag1.contains(43, 8)  # pushed to s1
            assert dict(frag0.blocks()) == dict(frag1.blocks())
        finally:
            for s in servers:
                s.close()

    def test_attr_sync(self, two_servers):
        from pilosa_tpu.sync.syncer import HolderSyncer

        s0, s1 = two_servers
        for s in (s0, s1):
            s.holder.create_index_if_not_exists("i")
            s.holder.index("i").create_frame_if_not_exists("f")
        # set attrs only on s0 (bypassing broadcast)
        s0.holder.index("i").column_attr_store.set_attrs(1, {"color": "red"})
        s0.holder.frame("i", "f").row_attr_store.set_attrs(2, {"tag": "x"})
        # sync from s1 pulls the diff
        HolderSyncer(
            holder=s1.holder, host=s1.host, cluster=s1.cluster
        ).sync_holder()
        assert s1.holder.index("i").column_attr_store.attrs(1) == {"color": "red"}
        assert s1.holder.frame("i", "f").row_attr_store.attrs(2) == {"tag": "x"}


class TestFailover:
    def test_read_failover_to_replica(self, tmp_path):
        """With replica_n=2, killing one node must not break reads: the
        coordinator re-maps its slices onto the surviving replica
        (reference: executor.go:1186-1197)."""
        clusters = [Cluster(replica_n=2) for _ in range(3)]
        servers = [
            Server(
                data_dir=str(tmp_path / f"f{i}"), cluster=clusters[i],
                anti_entropy_interval=3600, polling_interval=3600,
                cache_flush_interval=3600,
            )
            for i in range(3)
        ]
        for s in servers:
            s.open()
        try:
            hosts = sorted(s.host for s in servers)
            for c in clusters:
                for host in hosts:
                    if c.node_by_host(host) is None:
                        c.add_node(host)
                c.nodes.sort(key=lambda n: n.host)
            for s in servers:
                s.holder.create_index_if_not_exists("i")
                s.holder.index("i").create_frame_if_not_exists("f")

            coordinator = servers[0]
            c0 = InternalClient(coordinator.host, timeout=10.0)
            for sl in range(6):
                c0.execute_query(
                    "i", f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH})'
                )
            # No broadcaster in this fixture: learn the cluster max slice
            # through the polling loop (the static-cluster mechanism).
            coordinator._tick_max_slices()
            assert c0.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == 6

            # kill a non-coordinator node
            victim = servers[2]
            victim.close()
            assert c0.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == 6
            rb = c0.execute_pql("i", 'Bitmap(frame="f", rowID=1)')
            assert len(codec.bitmap_to_json(rb)["bits"]) == 6
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


class TestClusterFailure:
    """3 real servers, replica_n=2: kill a node mid-stream, assert exact
    answers via query-time failover re-map, restart it, repair via the
    syncer, and assert byte-identical fragment checksums (reference:
    server/server_test.go:279-497, executor.go:1186-1197)."""

    N_SLICES = 8

    def _boot(self, tmp_path, name, host="127.0.0.1:0"):
        s = Server(
            data_dir=str(tmp_path / name),
            host=host,
            cluster=Cluster(replica_n=2),
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
        )
        s.open()
        return s

    def _wire(self, servers, hosts):
        """Give every server the same ordered node list."""
        for s in servers:
            s.cluster.nodes = [
                n for n in s.cluster.nodes if n.host in hosts
            ]
            for h in hosts:
                if s.cluster.node_by_host(h) is None:
                    s.cluster.add_node(h)
            s.cluster.nodes.sort(key=lambda n: n.host)

    def test_kill_failover_restart_converge(self, tmp_path):
        servers = [self._boot(tmp_path, f"n{i}") for i in range(3)]
        try:
            hosts = sorted(s.host for s in servers)
            self._wire(servers, hosts)
            for s in servers:
                s.holder.create_index_if_not_exists("i")
                s.holder.index("i").create_frame_if_not_exists("f")

            c0 = InternalClient(servers[0].host, timeout=10.0)
            total = 0
            for sl in range(self.N_SLICES):
                for c in range(sl + 1):
                    c0.execute_query(
                        "i",
                        f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH + c})',
                    )
                    total += 1
            want = total  # 1+2+..+8 = 36

            # Max-slice convergence via the real polling tick (the
            # reference's passive path, server.go:238-274) — this
            # fixture wires no broadcaster.
            for s in servers:
                s._tick_max_slices()

            # sanity: every coordinator answers exactly
            for s in servers:
                cc = InternalClient(s.host, timeout=10.0)
                assert cc.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == want

            # ---- kill one node that owns data ----
            victim = servers[1]
            victim_host = victim.host
            victim_dir = victim.data_dir
            victim.close()

            # Queries from the surviving coordinators still answer
            # EXACTLY: the executor re-maps the dead node's slices to
            # replicas (executor.py failover loop).
            for s in (servers[0], servers[2]):
                cc = InternalClient(s.host, timeout=10.0)
                assert cc.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == want

            # Divergence the victim will have to repair: row-2 bits
            # applied directly on the surviving replica of each slice
            # (write fan-out to a dead replica errors, like the
            # reference — executor.go:810-840 returns the first remote
            # failure — so a real deployment diverges exactly this way:
            # the surviving replica applied its local write before the
            # forward failed).
            extra = 0
            for sl in range(self.N_SLICES):
                owners = [
                    n.host
                    for n in servers[0].cluster.fragment_nodes("i", sl)
                ]
                for s in (servers[0], servers[2]):
                    if s.host in owners:
                        s.holder.index("i").frame("f").set_bit(
                            "standard", 2, sl * SLICE_WIDTH + 99
                        )
                        extra += 1
                        break

            # ---- restart the victim on its old host:port ----
            revived = self._boot(tmp_path, "n1", host=victim_host)
            servers[1] = revived
            self._wire(servers, hosts)
            revived.holder.create_index_if_not_exists("i")
            revived.holder.index("i").create_frame_if_not_exists("f")
            revived._tick_max_slices()

            # The revived node missed row-2 writes (and some slices
            # diverged between the two survivors); anti-entropy runs on
            # EVERY node in production — run each node's syncer once.
            from pilosa_tpu.sync.syncer import HolderSyncer

            for s in servers:
                HolderSyncer(s.holder, s.host, s.cluster).sync_holder()

            # Convergence: every fragment's checksum is byte-identical
            # across the replicas that own it.
            for sl in range(self.N_SLICES):
                owners = {
                    n.host
                    for n in servers[0].cluster.fragment_nodes("i", sl)
                }
                sums = {}
                for s in servers:
                    if s.host not in owners:
                        continue
                    frag = s.holder.fragment("i", "f", "standard", sl)
                    assert frag is not None, (s.host, sl)
                    sums[s.host] = frag.checksum()
                assert len(sums) == 2, (sl, owners)
                assert len(set(sums.values())) == 1, (sl, sums)

            # And the revived coordinator answers exactly.
            cr = InternalClient(revived.host, timeout=10.0)
            assert cr.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == want
            assert cr.execute_pql("i", 'Count(Bitmap(frame="f", rowID=2))') == extra
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


class TestConcurrentLoad:
    def test_concurrent_writers_and_readers_exact(self, server):
        """8 writer threads (disjoint column ranges) + 4 reader threads
        hammer one server; no request may error, and the final count
        must be exactly the union of all writes (fragment/cache/executor
        locks under real HTTP concurrency)."""
        import concurrent.futures

        c = InternalClient(server.host, timeout=30.0)
        c.create_index("cc")
        c.create_frame("cc", "f")
        per_thread = 120

        def writer(t):
            cw = InternalClient(server.host, timeout=30.0)
            base = t * 1000
            changed = 0
            for i in range(per_thread):
                (res,) = cw.execute_query(
                    "cc", f'SetBit(frame="f", rowID=1, columnID={base + i})'
                )
                changed += bool(res)
            return changed  # every column is fresh: all must report changed

        def reader(_t):
            cr = InternalClient(server.host, timeout=30.0)
            last = 0
            for _ in range(40):
                n = cr.execute_pql("cc", 'Count(Bitmap(frame="f", rowID=1))')
                # monotonic under set-only writes
                assert n >= last, (n, last)
                last = n
            return last

        with concurrent.futures.ThreadPoolExecutor(12) as pool:
            w = [pool.submit(writer, t) for t in range(8)]
            r = [pool.submit(reader, t) for t in range(4)]
            total = sum(f.result() for f in w)
            for f in r:
                f.result()
        assert total == 8 * per_thread
        assert (
            c.execute_pql("cc", 'Count(Bitmap(frame="f", rowID=1))') == total
        )


# ---------------------------------------------------------------------------
# end-to-end gossip-backed cluster (reference: server/server_test.go:376-497)
# ---------------------------------------------------------------------------


class TestGossipCluster:
    """Three real servers discover each other through the actual
    GossipNodeSet (no manual broadcaster wiring): schema created on one
    node replicates through gossip state sync, membership drives node
    states, and every node — including one that joins late — answers
    queries."""

    @staticmethod
    def _gossip_server(tmp_path, name, hosts, seed=""):
        from pilosa_tpu.cluster.gossip import GossipNodeSet
        from tests.conftest import free_udp_port as free_udp

        cluster = Cluster(replica_n=1)
        ns = GossipNodeSet(
            host="placeholder",  # re-set once the HTTP port is known
            seed=seed,
            gossip_interval=0.05,
            suspect_after=5.0,
        )
        ns.bind = ("127.0.0.1", free_udp())
        cluster.node_set = ns
        s = Server(
            data_dir=str(tmp_path / name),
            cluster=cluster,
            broadcaster=ns,
            broadcast_receiver=ns,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
        )
        # Static placement list (reference config cluster.hosts); the
        # ports are pre-reserved by the caller.
        for h in hosts:
            cluster.add_node(h)
        return s, ns

    def test_three_nodes_discover_replicate_and_answer(self, tmp_path):
        import socket as _socket

        # Reserve three HTTP ports up front: the placement list must be
        # identical (and complete) on every node from the start.
        ports = []
        socks = []
        for _ in range(3):
            sk = _socket.socket()
            sk.bind(("127.0.0.1", 0))
            ports.append(sk.getsockname()[1])
            socks.append(sk)
        for sk in socks:
            sk.close()
        hosts = sorted(f"127.0.0.1:{p}" for p in ports)

        servers = []
        nodesets = []
        try:
            # Boot the first two; the third joins LATE.
            for i in range(2):
                s, ns = self._gossip_server(tmp_path, f"n{i}", hosts)
                s.host = hosts[i]
                ns.host = hosts[i]
                ns.advertise = ("127.0.0.1", ns.bind[1])
                if i > 0:
                    ns.seed = f"{nodesets[0].bind[0]}:{nodesets[0].bind[1]}"
                s.open()
                servers.append(s)
                nodesets.append(ns)

            c0 = InternalClient(servers[0].host, timeout=10.0)
            c0.create_index("i")
            c0.create_frame("i", "f")

            # Schema reaches node 1 via gossip state sync alone.
            deadline = time.time() + 10.0
            while time.time() < deadline:
                if servers[1].holder.frame("i", "f") is not None:
                    break
                time.sleep(0.05)
            assert servers[1].holder.frame("i", "f") is not None

            # Late joiner: node 2 boots now, seeds off node 0's gossip.
            s2, ns2 = self._gossip_server(
                tmp_path, "n2", hosts,
                seed=f"{nodesets[0].bind[0]}:{nodesets[0].bind[1]}",
            )
            s2.host = hosts[2]
            ns2.host = hosts[2]
            ns2.advertise = ("127.0.0.1", ns2.bind[1])
            s2.open()
            servers.append(s2)
            nodesets.append(ns2)

            deadline = time.time() + 10.0
            while time.time() < deadline:
                if s2.holder.frame("i", "f") is not None and len(
                    nodesets[0].nodes()
                ) == 3:
                    break
                time.sleep(0.05)
            assert s2.holder.frame("i", "f") is not None, "late joiner never synced schema"
            assert sorted(nodesets[0].nodes()) == hosts

            # Writes via the coordinator route to owners across all 3.
            cols = [s * SLICE_WIDTH + s for s in range(6)]
            for col in cols:
                c0.execute_query("i", f'SetBit(frame="f", rowID=1, columnID={col})')

            # Every node must know the cluster max slice before counting.
            deadline = time.time() + 10.0
            while time.time() < deadline:
                if all(
                    s.holder.index("i").max_slice() >= 5 for s in servers
                ):
                    break
                time.sleep(0.05)

            for s in servers:
                client = InternalClient(s.host, timeout=10.0)
                (n,) = client.execute_query(
                    "i", 'Count(Bitmap(rowID=1, frame="f"))'
                )
                assert int(n) == len(cols), f"count from {s.host}"
        finally:
            for s in servers:
                s.close()
