"""RowBitmap (segmented row) tests — parity tier for bitmap.go tests."""

import numpy as np

from pilosa_tpu.core.bitmap import RowBitmap
from pilosa_tpu.ops import bitplane as bp

SW = bp.SLICE_WIDTH


def test_from_bits_roundtrip():
    bits = [0, 5, SW - 1, SW, SW + 7, 3 * SW + 100]
    b = RowBitmap.from_bits(bits)
    assert b.bits() == sorted(bits)
    assert b.count() == len(bits)
    assert sorted(b.segments) == [0, 1, 3]


def test_intersect_union_difference_xor():
    a = RowBitmap.from_bits([1, 2, 3, SW + 1])
    b = RowBitmap.from_bits([2, 3, 4, 2 * SW + 9])
    assert a.intersect(b).bits() == [2, 3]
    assert a.union(b).bits() == [1, 2, 3, 4, SW + 1, 2 * SW + 9]
    assert a.difference(b).bits() == [1, SW + 1]
    assert a.xor(b).bits() == [1, 4, SW + 1, 2 * SW + 9]


def test_intersection_count():
    a = RowBitmap.from_bits([1, 2, 3, SW + 1, SW + 2])
    b = RowBitmap.from_bits([2, 3, SW + 2, 5 * SW])
    assert a.intersection_count(b) == 3


def test_merge_in_place():
    a = RowBitmap.from_bits([1, 2])
    b = RowBitmap.from_bits([2, 3, SW + 5])
    a.merge(b)
    assert a.bits() == [1, 2, 3, SW + 5]


def test_segment_count_memoized():
    a = RowBitmap.from_bits([1, 2, 3])
    assert a.segment_count(0) == 3
    # mutate under the hood: memo should still return 3 until invalidated
    seg = a.segments[0].copy()
    seg[0] |= np.uint32(1 << 10)
    a.segments[0] = seg
    assert a.segment_count(0) == 3
    a.invalidate_count()
    assert a.segment_count(0) == 4


def test_set_bit_and_json():
    b = RowBitmap()
    assert b.set_bit(42)
    assert not b.set_bit(42)
    assert b.to_json_dict() == {"attrs": {}, "bits": [42]}
    b.attrs = {"x": 1}
    assert b.to_json_dict() == {"attrs": {"x": 1}, "bits": [42]}


def test_equality():
    assert RowBitmap.from_bits([1, SW]) == RowBitmap.from_bits([SW, 1])
    assert RowBitmap.from_bits([1]) != RowBitmap.from_bits([2])
