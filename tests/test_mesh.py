"""Multi-device sharding tests on the virtual 8-device CPU mesh
(conftest.py forces XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pilosa_tpu.exec import plan
from pilosa_tpu.parallel import (
    AXIS_ROWS,
    AXIS_SLICES,
    distributed_count,
    distributed_topn,
    query_step,
    shard_planes,
    slice_mesh,
)
from pilosa_tpu.pql.parser import parse_string

W = 256  # tiny word axis: kernels are shape-agnostic


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def test_slice_mesh_shape():
    m = slice_mesh(8)
    assert m.shape == {AXIS_SLICES: 8, AXIS_ROWS: 1}
    m = slice_mesh(8, row_shards=2)
    assert m.shape == {AXIS_SLICES: 4, AXIS_ROWS: 2}
    with pytest.raises(ValueError):
        slice_mesh(8, row_shards=3)


def test_shard_planes_pads(rng):
    m = slice_mesh(8)
    planes = rng.integers(0, 2**32, size=(5, 4, W), dtype=np.uint32)
    arr = shard_planes(planes, m)
    assert arr.shape == (8, 4, W)
    np.testing.assert_array_equal(np.asarray(arr)[:5], planes)
    assert not np.asarray(arr)[5:].any()


def test_distributed_count_matches_host(rng):
    m = slice_mesh(8, row_shards=2)
    q = parse_string("Union(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)), Bitmap(rowID=3))")
    expr, leaves = plan.decompose(q.calls[0])
    n_leaves = len(leaves)
    planes = rng.integers(0, 2**32, size=(8, n_leaves, 4, W), dtype=np.uint32)
    sharded = jax.device_put(
        planes, NamedSharding(m, P(AXIS_SLICES, None, AXIS_ROWS, None))
    )
    got = distributed_count(expr, sharded)
    a, b, c = planes[:, 0], planes[:, 1], planes[:, 2]
    want = int(np.bitwise_count((a & b) | c).sum())
    assert got == want


def test_distributed_topn_matches_host(rng):
    m = slice_mesh(8)
    planes = rng.integers(0, 2**32, size=(8, 16, W), dtype=np.uint32)
    src = rng.integers(0, 2**32, size=(8, W), dtype=np.uint32)
    pl = jax.device_put(planes, NamedSharding(m, P(AXIS_SLICES, AXIS_ROWS, None)))
    sr = jax.device_put(src, NamedSharding(m, P(AXIS_SLICES, None)))
    counts, ids = distributed_topn(pl, sr, 4)
    want = np.bitwise_count(planes & src[:, None, :]).sum(axis=(0, 2))
    order = np.argsort(-want, kind="stable")[:4]
    np.testing.assert_array_equal(ids, order)
    np.testing.assert_array_equal(counts, want[order])


def test_query_step_end_to_end(rng):
    """The dryrun/bench step: scatter-OR writes, fused Intersect+Count,
    TopN — one compiled program over the mesh."""
    m = slice_mesh(8, row_shards=2)
    n_slices, rows, n_upd = 8, 8, 16
    planes = rng.integers(0, 2**32, size=(n_slices, rows, W), dtype=np.uint32)
    sharded = shard_planes(planes, m)
    # Unique (row, word) targets — query_step requires pre-combined
    # duplicates (see its docstring).
    flat = rng.choice(rows * W, size=n_upd, replace=False)
    rows_upd, words_upd = flat // W, flat % W
    masks = rng.integers(0, 2**32, size=(n_slices, n_upd), dtype=np.uint32)

    step = query_step(m)
    planes2, count, top_counts, top_ids = step(
        sharded, jnp.asarray(rows_upd), jnp.asarray(words_upd), jnp.asarray(masks)
    )

    # Host reference.
    ref = planes.copy()
    for i in range(n_upd):
        ref[:, rows_upd[i], words_upd[i]] |= masks[:, i]
    np.testing.assert_array_equal(np.asarray(planes2), ref)
    want_count = int(np.bitwise_count(ref[:, 0, :] & ref[:, 1, :]).sum())
    assert int(np.asarray(count, dtype=np.int64).sum()) == want_count
    per_row = np.bitwise_count(ref & ref[:, 0:1, :]).sum(axis=(0, 2))
    order = np.argsort(-per_row, kind="stable")[:4]
    np.testing.assert_array_equal(np.asarray(top_ids), order)
    np.testing.assert_array_equal(np.asarray(top_counts), per_row[order])


def test_on_device_count_reduce_emits_collective(rng):
    """The sharded Count program carries its cross-slice reduce as a
    compiled collective (all-reduce) — only the limb pair reaches the
    host (VERDICT r1 item 3; reference analog: the HTTP fan-in reduce in
    executor.go:1176-1207)."""
    m = slice_mesh(8)
    q = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
    expr, _ = plan.decompose(q.calls[0].children[0])
    planes = np.random.default_rng(3).integers(
        0, 2**32, size=(8, 2, W), dtype=np.uint32
    )
    batch = jax.device_put(planes, NamedSharding(m, P(AXIS_SLICES, None, None)))
    fn = plan.compiled_total_count(expr, m)
    hlo = fn.lower(batch).compile().as_text()
    assert "all-reduce" in hlo, hlo[:2000]
    got = plan.recombine_count_limbs(jax.device_get(fn(batch)))
    assert got == int(np.bitwise_count(planes[:, 0] & planes[:, 1]).sum())


def test_count_reduce_collective_at_4096_slices_past_int32(rng):
    """The two-stage limb reduce keeps the collective on-device far past
    the old 2047-slice int32 cliff (VERDICT r2 item 5): 4096 slices
    still compile to one all-reduce with two scalars home.  Word count
    is scaled down (the budget math is per-slice, not per-word);
    all-ones rows make every partial exactly 2^16 — each lands entirely
    in the hi limb, the shape the old single-int32 sum mis-handled
    beyond 2047 slices at full width."""
    m = slice_mesh(8)
    q = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
    expr, _ = plan.decompose(q.calls[0].children[0])
    n, w = 4096, 2048  # 4096 slices x 65536 bits/slice, all ones
    planes = np.full((n, 2, w), 0xFFFFFFFF, dtype=np.uint32)
    planes[:7, 0, 0] = 0x1  # a little asymmetry across shards
    batch = jax.device_put(planes, NamedSharding(m, P(AXIS_SLICES, None, None)))
    fn = plan.compiled_total_count(expr, m)
    hlo = fn.lower(batch).compile().as_text()
    assert "all-reduce" in hlo, hlo[:2000]
    got = plan.recombine_count_limbs(jax.device_get(fn(batch)))
    want = int(np.bitwise_count(planes[:, 0] & planes[:, 1]).sum())
    assert want > 2**27  # ~2^28 bits: far past any single-partial scale
    assert got == want


def test_count_reduce_4d_per_slice_total_past_int32():
    """Multi-row (4-D) batches whose PER-SLICE totals pass int32 stay
    exact: the limb split happens on per-(slice,row) partials BEFORE the
    row-axis sum — a single per-slice int32 accumulator would wrap at
    2^31 (code-review regression, r3)."""
    m = slice_mesh(2)
    q = parse_string("Count(Bitmap(rowID=1))")
    expr, _ = plan.decompose(q.calls[0].children[0])
    # 2 slices x 2048 full-width rows, all ones: per-slice total is
    # exactly 2^31 — one int32 step past INT32_MAX.
    rows, w = 2048, 32768
    planes = np.full((2, 1, rows, w), 0xFFFFFFFF, dtype=np.uint32)
    sharded = jax.device_put(
        planes, NamedSharding(m, P(AXIS_SLICES, None, AXIS_ROWS, None))
    )
    got = distributed_count(expr, sharded)
    assert got == 1 << 32


def test_count_reduce_limbs_exact_past_2_31_bits():
    """Totals beyond int32 range recombine exactly from the limbs:
    2^15 slices x 2^17 bits = 2^32 bits, the budget edge (BASELINE
    configs[4] 10B-column cluster shape fits well inside)."""
    m = slice_mesh(8)
    q = parse_string("Count(Bitmap(rowID=1))")
    expr, _ = plan.decompose(q.calls[0].children[0])
    n, w = 1 << 15, 4096  # 2^15 slices x 2^17 bits, all ones
    planes = np.full((n, 1, w), 0xFFFFFFFF, dtype=np.uint32)
    batch = jax.device_put(planes, NamedSharding(m, P(AXIS_SLICES, None, None)))
    got = plan.recombine_count_limbs(
        jax.device_get(plan.compiled_total_count(expr, m)(batch))
    )
    assert got == (1 << 32)  # > int32 max; limb math must be exact


def test_distributed_topn_reduce_on_device(rng):
    """distributed_topn's cross-slice sum compiles to a collective and
    transfers only the [rows] totals."""
    from pilosa_tpu.parallel import mesh as pmesh

    m = slice_mesh(8)
    planes = rng.integers(0, 2**32, size=(8, 16, W), dtype=np.uint32)
    src = rng.integers(0, 2**32, size=(8, W), dtype=np.uint32)
    pl = jax.device_put(planes, NamedSharding(m, P(AXIS_SLICES, AXIS_ROWS, None)))
    sr = jax.device_put(src, NamedSharding(m, P(AXIS_SLICES, None)))
    fn = pmesh._topn_total_fn(m)
    hlo = fn.lower(pl, sr).compile().as_text()
    assert "all-reduce" in hlo, hlo[:2000]
    per = plan.recombine_count_limbs(jax.device_get(fn(pl, sr)))
    want = np.bitwise_count(planes & src[:, None, :]).sum(axis=(0, 2))
    np.testing.assert_array_equal(per, want)


class TestShardedExecutor:
    """The executor's multi-device path: fragments pin planes to
    slice%n_devices and query batches assemble shard-local."""

    def _exec(self, tmp_path, n_slices=8):
        import jax

        from pilosa_tpu.core.holder import Holder
        from pilosa_tpu.exec.executor import Executor
        from pilosa_tpu.ops.bitplane import SLICE_WIDTH
        from pilosa_tpu.pql.parser import parse_string

        h = Holder(str(tmp_path / "data"))
        h.open()
        idx = h.create_index("i")
        f = idx.create_frame("f")
        for s in range(n_slices):
            f.set_bit("standard", 1, s * SLICE_WIDTH + s)
            if s % 2 == 0:
                f.set_bit("standard", 2, s * SLICE_WIDTH + s)
        ex = Executor(holder=h, host="local")
        return h, ex, parse_string

    def test_fragment_planes_pinned_round_robin(self, tmp_path):
        import jax

        h, ex, parse = self._exec(tmp_path)
        devs = jax.local_devices()
        assert len(devs) == 8  # conftest virtual mesh
        seen = set()
        for s in range(8):
            frag = h.fragment("i", "f", "standard", s)
            dev = list(frag.device_plane().devices())[0]
            assert dev == devs[s % len(devs)]
            seen.add(dev)
        assert len(seen) == 8  # spread over every device

    def test_sharded_count_matches_expected(self, tmp_path):
        h, ex, parse = self._exec(tmp_path)
        q = parse('Count(Bitmap(frame="f", rowID=1))')
        assert ex.execute("i", q) == [8]
        q = parse('Count(Intersect(Bitmap(frame="f", rowID=1), Bitmap(frame="f", rowID=2)))')
        assert ex.execute("i", q) == [4]

    def test_sharded_row_matches_expected(self, tmp_path):
        from pilosa_tpu.net import codec
        from pilosa_tpu.ops.bitplane import SLICE_WIDTH

        h, ex, parse = self._exec(tmp_path)
        q = parse('Bitmap(frame="f", rowID=1)')
        (bm,) = ex.execute("i", q)
        assert codec.bitmap_to_json(bm)["bits"] == [
            s * SLICE_WIDTH + s for s in range(8)
        ]

    def test_uneven_groups_pad_cleanly(self, tmp_path):
        # 11 slices over 8 devices: some devices own 2 slices, some 1.
        h, ex, parse = self._exec(tmp_path, n_slices=11)
        q = parse('Count(Bitmap(frame="f", rowID=1))')
        assert ex.execute("i", q) == [11]

    def test_count_uses_on_device_total(self, tmp_path):
        """Executor Count routes through the collective total-count
        program (one scalar back to host), not per-slice device_get."""
        h, ex, parse = self._exec(tmp_path)
        before = plan._compiled_total_count.cache_info()
        q = parse('Count(Bitmap(frame="f", rowID=1))')
        assert ex.execute("i", q) == [8]
        after = plan._compiled_total_count.cache_info()
        assert after.hits + after.misses == before.hits + before.misses + 1


    def test_cold_and_warm_assembly_identical(self, tmp_path):
        """The cold (host-blocks) and warm (device-gather) mesh batch
        assemblers share one placement helper and MUST produce identical
        pos_of layouts and batch contents for the same slice set — they
        are interchangeable producers for the same batch cache."""
        h, ex, parse = self._exec(tmp_path, n_slices=11)
        from pilosa_tpu.exec import plan as _plan

        call = parse(
            'Count(Intersect(Bitmap(frame="f", rowID=1),'
            ' Bitmap(frame="f", rowID=2)))'
        ).calls[0].children[0]
        _, leaves = _plan.decompose(call)
        slices = list(range(11))
        mesh = __import__(
            "pilosa_tpu.parallel.mesh", fromlist=["default_slices_mesh"]
        ).default_slices_mesh()
        assert mesh is not None

        cold_batch, cold_pos, cold_kept, cold_emp = (
            ex._assemble_mesh_batch_host("i", leaves, slices, mesh)
        )
        expr, stacks, kept, emp = ex._gather_leaf_stacks("i", call, slices)
        warm_batch, warm_pos = ex._assemble_mesh_batch(stacks, kept, mesh)

        assert cold_kept == kept and cold_emp == emp
        assert cold_pos == warm_pos
        np.testing.assert_array_equal(
            np.asarray(cold_batch), np.asarray(warm_batch)
        )


class TestShardedByDefault:
    """ISSUE 12 acceptance: with >1 device visible, mesh-sharded
    execution engages BY DEFAULT — no config required — and the
    ``[device] mesh-devices`` knob can force it off (1) or cap it."""

    def _executor(self, tmp_path, n_slices=8):
        from pilosa_tpu.core.holder import Holder
        from pilosa_tpu.exec.executor import Executor
        from pilosa_tpu.ops.bitplane import SLICE_WIDTH

        h = Holder(str(tmp_path / "data"))
        h.open()
        idx = h.create_index("i")
        f = idx.create_frame("f")
        for s in range(n_slices):
            f.set_bit("standard", 1, s * SLICE_WIDTH + s)
            f.set_bit("standard", 2, s * SLICE_WIDTH + s)
        return h, Executor(holder=h, host="local")

    def test_default_batch_is_mesh_sharded(self, tmp_path):
        from pilosa_tpu.ops import bitplane as bp
        from pilosa_tpu.parallel import mesh as pmesh
        from pilosa_tpu.pql.parser import parse_string

        assert bp.mesh_device_count() == 8  # no knob, all visible
        h, ex = self._executor(tmp_path)
        try:
            call = parse_string(
                'Count(Intersect(Bitmap(frame="f", rowID=1),'
                ' Bitmap(frame="f", rowID=2)))'
            ).calls[0].children[0]
            ent = ex._cached_batch("i", call, list(range(8)))
            assert ent["mesh"] is not None, (
                "sharded execution must engage by default with >1 device"
            )
            assert ent["mesh"] is pmesh.default_slices_mesh()
            assert len(ent["batch"].devices()) == 8
        finally:
            ex.close()
            h.close()

    def test_mesh_devices_1_forces_single_device(self, tmp_path):
        import jax

        from pilosa_tpu.ops import bitplane as bp
        from pilosa_tpu.parallel import mesh as pmesh
        from pilosa_tpu.pql.parser import parse_string

        bp.configure_mesh_devices(1)
        try:
            assert bp.mesh_device_count() == 1
            assert pmesh.default_slices_mesh() is None
            h, ex = self._executor(tmp_path)
            try:
                call = parse_string(
                    'Count(Bitmap(frame="f", rowID=1))'
                ).calls[0].children[0]
                ent = ex._cached_batch("i", call, list(range(8)))
                assert ent["mesh"] is None
                assert list(ent["batch"].devices()) == [jax.local_devices()[0]]
                q = parse_string('Count(Bitmap(frame="f", rowID=1))')
                assert ex.execute("i", q) == [8]
            finally:
                ex.close()
                h.close()
        finally:
            bp.configure_mesh_devices(0)
            pmesh._slices_mesh = None

    def test_mesh_devices_env_caps(self, monkeypatch):
        from pilosa_tpu.ops import bitplane as bp

        monkeypatch.setenv("PILOSA_DEVICE_MESH_DEVICES", "4")
        assert bp.mesh_device_count() == 4
        monkeypatch.setenv("PILOSA_DEVICE_MESH_DEVICES", "0")
        assert bp.mesh_device_count() == 8  # 0 = all visible
        # malformed values never silently disable sharding
        monkeypatch.setenv("PILOSA_DEVICE_MESH_DEVICES", "bogus")
        assert bp.mesh_device_count() == 8
        # explicit configure wins over env
        bp.configure_mesh_devices(2)
        try:
            monkeypatch.setenv("PILOSA_DEVICE_MESH_DEVICES", "4")
            assert bp.mesh_device_count() == 2
        finally:
            bp.configure_mesh_devices(0)

    def test_server_applies_mesh_devices(self, tmp_path):
        from pilosa_tpu.net.server import Server
        from pilosa_tpu.ops import bitplane as bp
        from pilosa_tpu.parallel import mesh as pmesh

        s = Server(
            data_dir=str(tmp_path / "data"),
            host="127.0.0.1:0",
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
            mesh_devices=1,
        )
        s.open()
        try:
            assert bp.mesh_device_count() == 1
        finally:
            s.close()
            bp.configure_mesh_devices(0)
            pmesh._slices_mesh = None

    def test_config_knob_roundtrip(self):
        from pilosa_tpu import config as config_mod

        cfg = config_mod.from_toml("[device]\nmesh-devices = 1\n")
        assert cfg.device.mesh_devices == 1
        assert "mesh-devices = 1" in cfg.to_toml()
        cfg2 = config_mod.Config()
        config_mod.apply_env(
            cfg2, {"PILOSA_DEVICE_MESH_DEVICES": "4"}
        )
        assert cfg2.device.mesh_devices == 4
        cfg2.device.mesh_devices = -1
        with pytest.raises(config_mod.ConfigError):
            cfg2.validate()


def test_total_reduce_fused_over_mesh(rng):
    """The fused multi-query "total" reduce: K distinct Count trees in
    ONE interpreter pass over a sharded batch, the cross-slice sum as a
    compiled all-reduce — only limb pairs reach the host."""
    from pilosa_tpu.ops import bitplane as bp

    m = slice_mesh(8)
    planes = rng.integers(0, 2**32, size=(8, 3, W), dtype=np.uint32)
    batch = jax.device_put(
        planes, NamedSharding(m, P(AXIS_SLICES, None, None))
    )
    em = plan.FuseEmitter(4)
    r_and = plan.lower_expr(("Intersect", ("leaf", 0), ("leaf", 1)), 0, em)
    r_or = plan.lower_expr(
        ("Union", ("leaf", 0), ("leaf", 1), ("leaf", 2)), 0, em
    )
    prog = np.zeros((8, 4), dtype=np.int32)
    prog[: len(em.rows)] = np.asarray(em.rows, dtype=np.int32)
    out_idx = np.asarray([r_and, r_or], dtype=np.int32)
    # Leaf axis pads to the emitter's bucket (4).
    padded = jax.device_put(
        np.pad(planes, ((0, 0), (0, 1), (0, 0))),
        NamedSharding(m, P(AXIS_SLICES, None, None)),
    )
    fn = plan.compiled_interp("total")
    hlo = fn.fn.lower(padded, prog, out_idx).compile().as_text()
    assert "all-reduce" in hlo, hlo[:2000]
    res = np.asarray(jax.device_get(plan.interp_exec("total", padded, prog, out_idx)))
    assert res.shape == (2, 2)
    totals = plan.recombine_count_limbs(res)
    a, b, c = planes[:, 0], planes[:, 1], planes[:, 2]
    assert int(totals[0]) == int(np.bitwise_count(a & b).sum())
    assert int(totals[1]) == int(np.bitwise_count(a | b | c).sum())


def test_mesh_shape_config_caps_devices(monkeypatch):
    from pilosa_tpu.ops import bitplane as bp
    from pilosa_tpu.parallel import mesh as pmesh

    monkeypatch.setenv("PILOSA_TPU_MESH_SHAPE", "2x2")
    assert bp.mesh_device_count() == 4
    # placement stays within the capped mesh
    import jax

    devs = jax.local_devices()[:4]
    for s in range(8):
        assert bp.home_device(s) == devs[s % 4]
    # the slices mesh respects the cap
    mesh = pmesh.default_slices_mesh()
    assert mesh is not None and mesh.devices.size == 4
    pmesh._slices_mesh = None  # reset the cached mesh for other tests
    monkeypatch.setenv("PILOSA_TPU_MESH_SHAPE", "1")
    assert bp.mesh_device_count() == 1
    # malformed / non-positive values never silently disable sharding
    for bad in ("bogus", "x", "0", "0x4", "-2"):
        monkeypatch.setenv("PILOSA_TPU_MESH_SHAPE", bad)
        assert bp.mesh_device_count() == 8, bad


def test_multihost_initialize_unconfigured_noop(monkeypatch):
    """Without JAX_COORDINATOR_ADDRESS, initialize() is a no-op (the
    configured 1-process-group path runs in a subprocess below)."""
    from pilosa_tpu.parallel import multihost

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    multihost.initialize()
    assert multihost.global_device_count() == 8
    assert not multihost.is_multihost()


# '' = a 1-process jax.distributed group boots here; otherwise the
# error text.  Probed once per session (the boot takes seconds) so the
# multihost subprocess tests SKIP — not fail — on hosts whose jax
# build or sandbox can't form a process group at all.
_multihost_probe_result: str | None = None


def _multihost_unavailable() -> str:
    global _multihost_probe_result
    if _multihost_probe_result is not None:
        return _multihost_probe_result
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
        JAX_NUM_PROCESSES="1",
        JAX_PROCESS_ID="0",
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", (
                "import jax; jax.config.update('jax_platforms', 'cpu')\n"
                "from pilosa_tpu.parallel import multihost\n"
                "multihost.initialize()\n"
                "assert jax.process_count() == 1\n"
                "print('probe ok')\n"
            )],
            env=env, capture_output=True, text=True, timeout=120,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        if out.returncode == 0 and "probe ok" in out.stdout:
            _multihost_probe_result = ""
        else:
            _multihost_probe_result = (out.stderr or out.stdout)[-300:]
    except subprocess.TimeoutExpired:
        _multihost_probe_result = "probe timed out"
    return _multihost_probe_result


def _require_multihost():
    err = _multihost_unavailable()
    if err:
        pytest.skip(f"jax.distributed cannot boot here: {err}")


def test_multihost_initialize_single_process_group():
    """The configured path joins a real 1-process group (subprocess:
    jax.distributed can only initialize once per process) and the second
    initialize() call is an idempotent no-op."""
    import os
    import socket
    import subprocess
    import sys

    _require_multihost()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
        JAX_NUM_PROCESSES="1",
        JAX_PROCESS_ID="0",
    )
    out = subprocess.run(
        [sys.executable, "-c", (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from pilosa_tpu.parallel import multihost\n"
            "multihost.initialize()\n"
            "multihost.initialize()\n"
            "print('pc', jax.process_count())\n"
        )],
        env=env, capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-800:]
    assert "pc 1" in out.stdout



def _run_multihost_pair(tmp_path, script_text, marker):
    """Boot a REAL 2-process jax.distributed group (4 CPU devices each)
    running ``script_text``; assert both processes print ``marker <pid>
    <token>`` and return the two tokens."""
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    script = tmp_path / "mh_worker.py"
    script.write_text(script_text)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def env_for(pid: int):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
        )
        # sys.path[0] is the script's dir (tmp), not the cwd — the repo
        # needs to be importable explicitly.
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        flags = env.get("XLA_FLAGS", "")
        flags = " ".join(
            f
            for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        )
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=4".strip()
        )
        return env

    procs = [
        subprocess.Popen(
            [sys.executable, str(script)],
            env=env_for(pid),
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]
    tokens = []
    for pid, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, err[-1500:]
        assert f"{marker} {pid}" in out, out
        tokens.append(out.strip().split()[-1])
    return tokens


_MULTIHOST_WORKER = """
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from pilosa_tpu.parallel import multihost
from pilosa_tpu.exec import plan
from pilosa_tpu.pql.parser import parse_string

multihost.initialize()
assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 8, len(devs)
mesh = Mesh(np.array(devs), ('slices',))

# Same full array in every process; each contributes its local shards.
rng = np.random.default_rng(5)
planes = rng.integers(0, 2**32, size=(8, 2, 256), dtype=np.uint32)
sharding = NamedSharding(mesh, P('slices', None, None))
batch = jax.make_array_from_callback(planes.shape, sharding,
                                     lambda idx: planes[idx])

q = parse_string('Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))')
expr, _ = plan.decompose(q.calls[0].children[0])
total = plan.recombine_count_limbs(
    jax.device_get(plan.compiled_total_count(expr, mesh)(batch)))
want = int(np.bitwise_count(planes[:, 0] & planes[:, 1]).sum())
assert total == want, (total, want)
print('MH OK', jax.process_index(), total, flush=True)
"""


def test_multihost_two_process_sharded_count(tmp_path):
    """A REAL 2-process jax.distributed group (4 CPU devices each, 8
    global): the sharded Count collective crosses the process boundary
    and both processes see the oracle total (VERDICT r1 item 8;
    reference analog: multi-node server tests,
    server/server_test.go:279-374)."""
    _require_multihost()
    totals = _run_multihost_pair(tmp_path, _MULTIHOST_WORKER, "MH OK")
    assert len(set(totals)) == 1  # both processes agree on the total


_MULTIHOST_TOPN_WORKER = """
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from pilosa_tpu.parallel import multihost, mesh as pmesh

multihost.initialize()
assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 8, len(devs)
mesh = Mesh(np.array(devs), ('slices',))

rng = np.random.default_rng(9)
planes = rng.integers(0, 2**32, size=(8, 16, 256), dtype=np.uint32)
src = rng.integers(0, 2**32, size=(8, 256), dtype=np.uint32)
p_sh = NamedSharding(mesh, P('slices', None, None))
s_sh = NamedSharding(mesh, P('slices', None))
plane = jax.make_array_from_callback(planes.shape, p_sh, lambda i: planes[i])
srcb = jax.make_array_from_callback(src.shape, s_sh, lambda i: src[i])

counts, ids = pmesh.distributed_topn(plane, srcb, 5)
want_per = np.bitwise_count(planes & src[:, None, :]).sum(axis=(0, 2))
want_ids = np.argsort(-want_per, kind='stable')[:5]
assert list(ids) == list(want_ids), (ids, want_ids)
assert list(counts) == [int(want_per[i]) for i in want_ids], counts
print('MHT OK', jax.process_index(),
      ','.join(f'{i}:{c}' for i, c in zip(ids, counts)), flush=True)
"""


def test_multihost_two_process_sharded_topn(tmp_path):
    """The distributed TopN scorer over a REAL 2-process jax.distributed
    group: the per-row cross-slice limb all-reduce crosses the process
    boundary and both processes rank identically to the numpy oracle
    (the DCN analog of the reference's TopN reduce over HTTP,
    executor.go:281-321)."""
    _require_multihost()
    tokens = _run_multihost_pair(tmp_path, _MULTIHOST_TOPN_WORKER, "MHT OK")
    # Each token is "id:count,..." — both processes must emit the same
    # ranked (id, count) sequence, already oracle-checked in-worker.
    assert len(set(tokens)) == 1
