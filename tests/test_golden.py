"""Golden roaring-format interop fixtures.

The files under tests/golden/ are constructed byte-by-byte from the
format spec by make_fixtures.py — independently of pilosa_tpu.ops.roaring
— so they act as an external oracle: a header/offset/op-log deviation in
our encoder or decoder cannot self-validate through a round-trip test
(reference format: roaring/roaring.go:507-660).

Covered edges: array<->bitmap boundary (n=4096/4097), multi-container
rows with non-contiguous and very high keys, op-log add/remove replay
after a snapshot, empty-container dropping on re-encode, and rejection
of corrupted offsets/payloads — checked through BOTH the pure-Python
decoder and (when built) the C++ codec.
"""

import json
import os
import struct

import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.ops import roaring

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")

with open(os.path.join(GOLDEN, "expected.json")) as fh:
    EXPECTED = json.load(fh)

FIXTURES = sorted(EXPECTED)


def load(name: str) -> bytes:
    with open(os.path.join(GOLDEN, name + ".roaring"), "rb") as fh:
        return fh.read()


def containers_to_bits(containers) -> list[int]:
    vals = []
    for key, words in containers.items():
        bits = np.unpackbits(words.view(np.uint8), bitorder="little")
        (pos,) = np.nonzero(bits)
        vals.extend(int(key) * roaring.CONTAINER_BITS + int(p) for p in pos)
    return sorted(vals)


def python_decode(data: bytes):
    """Force the pure-Python path (bypasses the native dispatch)."""
    containers, ops_offset, _ = roaring._decode_containers(data)
    op_n = roaring._apply_ops(containers, data, ops_offset)
    return containers, op_n


@pytest.mark.parametrize("name", FIXTURES)
def test_python_decode_matches_expected(name):
    containers, op_n = python_decode(load(name))
    assert containers_to_bits(containers) == EXPECTED[name]["bits"]
    assert op_n == EXPECTED[name]["ops"]


@pytest.mark.parametrize("name", FIXTURES)
def test_native_decode_matches_expected(name):
    if not native.available():
        pytest.skip("native codec not built")
    res = native.decode(load(name))
    assert res is not None
    containers, op_n = res
    assert containers_to_bits(containers) == EXPECTED[name]["bits"]
    assert op_n == EXPECTED[name]["ops"]


@pytest.mark.parametrize("name", FIXTURES)
def test_native_tiered_decode_matches_expected(name):
    if not native.available():
        pytest.skip("native codec not built")
    res = native.decode_tiered(load(name))
    assert res is not None
    words, arrays, op_n = res
    bits = []
    for key, w in words.items():
        vals = roaring.words_to_values(w)
        bits.extend(int(key) * roaring.CONTAINER_BITS + int(v) for v in vals)
    for key, vals in arrays.items():
        bits.extend(int(key) * roaring.CONTAINER_BITS + int(v) for v in vals)
    assert sorted(bits) == EXPECTED[name]["bits"]
    assert op_n == EXPECTED[name]["ops"]


@pytest.mark.parametrize("name", FIXTURES)
def test_check_and_info_accept(name):
    data = load(name)
    assert roaring.check(data) == []
    info = roaring.info(data)
    assert info.ops == EXPECTED[name]["ops"]


@pytest.mark.parametrize("name", FIXTURES)
def test_reencode_roundtrip(name):
    """Decoding a golden file and re-encoding must preserve the exact
    bit-set; containers emptied by the op-log must be dropped."""
    containers, _ = python_decode(load(name))
    data2 = roaring.encode(containers)
    got = containers_to_bits(roaring.decode(data2))
    assert got == EXPECTED[name]["bits"]


def test_boundary_forms():
    """n=4096 must be array form (4 bytes/value), n=4097 bitmap (8 KiB)."""
    info = roaring.info(load("array_boundary_4096"))
    assert [c.type for c in info.containers] == ["array"]
    assert info.containers[0].n == 4096
    info = roaring.info(load("bitmap_boundary_4097"))
    assert [c.type for c in info.containers] == ["bitmap"]
    assert info.containers[0].n == 4097
    assert info.containers[0].alloc == 8192


def test_empty_container_dropped_on_reencode():
    containers, _ = python_decode(load("oplog_empties_container"))
    # decode keeps the (now all-zero) container in memory...
    assert containers_to_bits(containers) == []
    # ...but re-encode must not serialize it (reference skips c.n == 0).
    data2 = roaring.encode(containers)
    assert struct.unpack_from("<II", data2, 0)[1] == 0
    assert roaring.check(data2) == []


def test_fragment_loads_golden_rows(tmp_path):
    """A golden file drops straight into a Fragment: the multi-container
    fixture spans rows {0, 1, 2, 2^26} of slice 0."""
    from pilosa_tpu.core.fragment import Fragment

    path = tmp_path / "frag"
    path.write_bytes(load("multi_container"))
    f = Fragment(str(path), "i", "f", "standard", 0)
    f.open()
    try:
        expected_rows = sorted(
            {b // bp.SLICE_WIDTH for b in EXPECTED["multi_container"]["bits"]}
        )
        got_rows = sorted(f._slot_of)
        assert got_rows == expected_rows
        got_bits = sorted(
            r * bp.SLICE_WIDTH + (c % bp.SLICE_WIDTH) for r, c in f.for_each_bit()
        )
        assert got_bits == EXPECTED["multi_container"]["bits"]
    finally:
        f.close()


@pytest.mark.parametrize("decoder", ["python", "native"])
def test_corrupted_offset_rejected(decoder):
    """An offset pointing past EOF must be rejected, not crash or read
    garbage."""
    if decoder == "native" and not native.available():
        pytest.skip("native codec not built")
    data = bytearray(load("multi_container"))
    (count,) = struct.unpack_from("<I", data, 4)
    offtab_at = 8 + count * 12
    struct.pack_into("<I", data, offtab_at, len(data) + 100)
    if decoder == "python":
        with pytest.raises(roaring.CorruptError, match="out of bounds"):
            python_decode(bytes(data))
    else:
        with pytest.raises(native.NativeCorruptError):
            native.decode(bytes(data))
    assert roaring.check(bytes(data))  # reported as a problem, not a crash


def test_corrupted_op_checksum_rejected():
    data = bytearray(load("oplog_after_snapshot"))
    data[-1] ^= 0xFF  # flip a bit in the last op's FNV checksum
    with pytest.raises(roaring.CorruptError, match="checksum"):
        roaring.decode(bytes(data))


def test_truncated_bitmap_payload_rejected():
    data = load("bitmap_boundary_4097")
    with pytest.raises(roaring.CorruptError, match="out of bounds"):
        roaring.decode(data[: len(data) - 8])
