"""Native C++ codec: build, parity vs the Python codec, CSV parsing.

The analog of the reference's asm-vs-Go popcount equivalence tests
(reference: roaring/assembly_test.go:20-43): every native path must be
byte-identical with the pure-Python implementation.
"""

import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.ops import roaring

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


import contextlib
from unittest import mock


@contextlib.contextmanager
def _python_codec():
    """Run the real roaring codec with native dispatch disabled, so
    parity is always measured against the actual fallback path."""
    with mock.patch.object(native, "decode", return_value=None), \
         mock.patch.object(native, "encode", return_value=None):
        yield


def _py_encode(containers):
    with _python_codec():
        return roaring.encode(containers)


def _py_decode(data):
    with _python_codec():
        return roaring.decode_with_ops(data)


def _random_containers(rng, n_containers=6):
    out = {}
    keys = rng.choice(200, size=n_containers, replace=False)
    for i, key in enumerate(sorted(int(k) for k in keys)):
        words = np.zeros(1024, dtype=np.uint64)
        if i % 3 == 0:
            # sparse (array form)
            positions = rng.choice(65536, size=rng.integers(1, 100), replace=False)
            for p in positions:
                words[p // 64] |= np.uint64(1) << np.uint64(p % 64)
        elif i % 3 == 1:
            # dense (bitmap form)
            words[:] = rng.integers(0, 2**64, size=1024, dtype=np.uint64)
        # else: empty container (must be dropped on encode)
        out[key] = words
    return out


class TestNativeParity:
    def test_encode_byte_identical(self, rng):
        containers = _random_containers(rng)
        assert native.encode(containers) == _py_encode(containers)

    def test_decode_matches_python(self, rng):
        containers = _random_containers(rng)
        data = _py_encode(containers)
        # append an op-log
        data += roaring.encode_op(roaring.OP_ADD, 12345)
        data += roaring.encode_op(roaring.OP_ADD, 99 * 65536 + 7)
        data += roaring.encode_op(roaring.OP_REMOVE, 12345)
        nat, nat_ops = native.decode(data)
        py, py_ops = _py_decode(data)
        assert nat_ops == py_ops == 3
        assert sorted(nat) == sorted(py)
        for k in py:
            np.testing.assert_array_equal(nat[k], py[k])

    def test_roundtrip_through_dispatch(self, rng):
        """roaring.encode/decode dispatch through native and round-trip."""
        containers = {
            k: w for k, w in _random_containers(rng).items() if w.any()
        }
        data = roaring.encode(containers)
        back = roaring.decode(data)
        assert sorted(back) == sorted(containers)
        for k in containers:
            np.testing.assert_array_equal(back[k], containers[k])

    def test_encode_packed_byte_identical(self, rng):
        """encode_packed (the snapshot/tar serializer) must produce the
        same bytes as the general dict path, native or not."""
        containers = {
            k: w for k, w in _random_containers(rng).items() if w.any()
        }
        keys = np.array(sorted(containers), dtype=np.uint64)
        words2d = np.stack([containers[int(k)] for k in keys])
        want = _py_encode(containers)
        assert roaring.encode_packed(keys, words2d) == want
        assert native.encode_packed(keys, words2d) == want
        # Python fallback of the packed entry point (no native lib)
        with _python_codec():
            assert roaring.encode_packed(keys, words2d) == want

    def test_encode_packed_mixed_tiers(self, rng):
        """Mixed dense+sparse tiers route through the general fallback
        and must byte-match an all-dict encode of the same content."""
        dense = {
            k: w for k, w in _random_containers(rng).items() if w.any()
        }
        arrays = {1000: np.array([1, 5, 65535], dtype=np.uint32)}
        keys = np.array(sorted(dense), dtype=np.uint64)
        words2d = np.stack([dense[int(k)] for k in keys])
        got = roaring.encode_packed(keys, words2d, arrays)
        want = roaring.encode_tiered(dict(dense), dict(arrays))
        assert got == want

    def test_encode_packed_rejects_bad_shape(self):
        import pytest

        with pytest.raises(ValueError):
            native.encode_packed(
                np.array([1], dtype=np.uint64),
                np.zeros((1, 1023), dtype=np.uint64),
            )

    def test_encode_op_identical(self):
        for typ, value in ((0, 0), (1, 7), (0, 2**63 + 5)):
            want = (
                bytes([typ])
                + value.to_bytes(8, "little")
                + roaring.fnv1a32(
                    bytes([typ]) + value.to_bytes(8, "little")
                ).to_bytes(4, "little")
            )
            assert native.encode_op(typ, value) == want
            assert roaring.encode_op(typ, value) == want

    def test_corrupt_rejected(self):
        with pytest.raises(native.NativeCorruptError):
            native.decode(b"\x00" * 16)
        # dispatch layer translates to CorruptError
        with pytest.raises(roaring.CorruptError):
            roaring.decode(b"\x00" * 16)

    def test_bad_op_checksum(self, rng):
        containers = {5: np.zeros(1024, dtype=np.uint64)}
        containers[5][0] = 1
        data = _py_encode(containers)
        op = bytearray(roaring.encode_op(roaring.OP_ADD, 1))
        op[-1] ^= 0xFF  # break the checksum
        with pytest.raises(roaring.CorruptError):
            roaring.decode(data + bytes(op))


class TestNativeCSV:
    def test_parse_basic(self):
        rows, cols = native.parse_csv(b"1,2\n3,4\n\n5,6\n")
        assert rows.tolist() == [1, 3, 5]
        assert cols.tolist() == [2, 4, 6]

    def test_crlf(self):
        rows, cols = native.parse_csv(b"1,2\r\n3,4\r\n")
        assert rows.tolist() == [1, 3]

    def test_no_trailing_newline(self):
        rows, cols = native.parse_csv(b"1,2\n3,4")
        assert rows.tolist() == [1, 3]

    def test_timestamp_column_falls_back(self):
        assert native.parse_csv(b"1,2,2024-01-01T00:00\n") is None

    def test_malformed_falls_back(self):
        assert native.parse_csv(b"a,b\n") is None
        assert native.parse_csv(b"1\n") is None

    def test_u64_overflow_falls_back(self):
        # 2^64+1 must not silently wrap to 1
        assert native.parse_csv(b"18446744073709551617,5\n") is None
        assert native.parse_csv(b"1,18446744073709551617\n") is None
        # but u64 max itself is fine
        rows, cols = native.parse_csv(b"18446744073709551615,5\n")
        assert rows.tolist() == [18446744073709551615]

    def test_large(self, rng):
        n = 50_000
        r = rng.integers(0, 1000, n)
        c = rng.integers(0, 10_000_000, n)
        blob = "\n".join(f"{a},{b}" for a, b in zip(r, c)).encode() + b"\n"
        rows, cols = native.parse_csv(blob)
        assert rows.tolist() == r.tolist()
        assert cols.tolist() == c.tolist()


class TestFormatCSV:
    def test_round_trip_with_parse(self, rng):
        n = 50_000
        r = rng.integers(0, 1000, n).astype(np.uint64)
        c = rng.integers(0, 10_000_000, n).astype(np.uint64)
        blob = native.format_csv(r, c)
        if blob is None:
            pytest.skip("native library unavailable")
        rows, cols = native.parse_csv(blob)
        assert rows.tolist() == r.tolist()
        assert cols.tolist() == c.tolist()

    def test_edge_values(self):
        r = np.array([0, 18446744073709551615], dtype=np.uint64)
        c = np.array([18446744073709551615, 0], dtype=np.uint64)
        blob = native.format_csv(r, c)
        if blob is None:
            pytest.skip("native library unavailable")
        assert blob == (
            b"0,18446744073709551615\n18446744073709551615,0\n"
        )

    def test_empty(self):
        blob = native.format_csv(
            np.empty(0, np.uint64), np.empty(0, np.uint64)
        )
        assert blob in (b"", None)
