"""The rolling-restart fast path (ROADMAP 2b).

The serving contract: a restarted node answers its first query well
under 1 s while its device mirrors are still streaming into HBM in the
background.  The eager ``warm_device_mirrors`` loop this replaces
serialized the whole mirror set (~254 MB, cold e2e 4.79 s) before the
first answer.

Covered here: the acceptance bar itself (first answer < 1 s with a
deliberately slowed single-worker Prefetcher and the staging job still
in flight), the staging priority order (gossip-hot slices, then the
persisted pre-restart residency table MRU-first, then the cold tail),
the residency table round-trip through ``Holder.close()``, the
``device.stage.*`` error accounting that replaced the silent log line,
and the gossip hot-slice piggyback feeding the priority head.
"""

from __future__ import annotations

import time

import pytest

from pilosa_tpu import device as device_mod
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.device.pool import PlanePool
from pilosa_tpu.device.prefetch import Prefetcher, StageJob
from pilosa_tpu.exec.executor import Executor
from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.pql.parser import parse_string

N_SLICES = 10
# Row 1 holds columns [0, 100), row 2 [50, 150) within each slice:
# |row1 AND row2| == 50 per slice.
PER_SLICE_AND = 50


@pytest.fixture
def fresh_pool():
    p = PlanePool()
    prev = device_mod._set_pool(p)
    yield p
    device_mod._set_pool(prev)


def _build(path: str, frames=("f", "g")) -> Holder:
    holder = Holder(path)
    holder.open()
    idx = holder.create_index("i")
    for name in frames:
        f = idx.create_frame(name)
        view = f.create_view_if_not_exists("standard")
        for s in range(N_SLICES):
            frag = view.create_fragment_if_not_exists(s)
            base = s * bp.SLICE_WIDTH
            for c in range(100):
                frag.set_bit(1, base + c)
                frag.set_bit(2, base + 50 + c)
            frag.flush_ops()
    return holder

QUERY = (
    "Count(Intersect(Bitmap(rowID=1, frame=g), Bitmap(rowID=2, frame=g)))"
)


class TestFirstAnswerOverlapsStaging:
    def test_first_query_under_1s_with_staging_in_flight(
        self, tmp_path, fresh_pool
    ):
        """The acceptance bar: with background staging deliberately
        slowed (one worker, 250 ms between uploads), the first
        post-restart query still answers in < 1 s — its own slices
        jump the backlog through the prefetcher's query lane — and the
        staging job is STILL in flight when the answer lands."""
        holder = _build(str(tmp_path))
        # Pre-restart incarnation: mirrors resident, programs compiled
        # (in-process analog of the persistent XLA compile cache).
        holder.warm_device_mirrors()
        ex = Executor(holder)
        q = parse_string(QUERY)
        (want,) = ex.execute("i", q)
        assert int(want) == PER_SLICE_AND * N_SLICES
        ex.close()
        holder.close()  # persists the residency table

        # "Restart": device state gone, data reopened from disk.
        device_mod._set_pool(PlanePool())
        h2 = Holder(str(tmp_path))
        h2.open()
        pf = Prefetcher(max_workers=1)
        job = h2.stage_device_mirrors(pf, throttle_s=0.25)
        # Both frames' fragments were cold, so the backlog at one
        # upload per 250 ms needs ~5 s — far past the first answer.
        assert job.total == 2 * N_SLICES
        ex2 = Executor(h2, prefetcher=pf)
        t0 = time.perf_counter()
        (got,) = ex2.execute("i", parse_string(QUERY))
        elapsed = time.perf_counter() - t0
        assert int(got) == int(want)
        assert elapsed < 1.0, f"first post-restart answer took {elapsed:.2f}s"
        assert not job.done(), "staging should still be in flight"
        assert job.wait(timeout=30.0)
        snap = job.snapshot()
        assert snap["remaining"] == 0
        assert snap["errors"] == 0
        # Every scheduled fragment either staged in the background or
        # was already resident because the query path got there first.
        assert snap["staged"] + snap["skipped"] == job.total
        pool_stage = device_mod.pool().snapshot()["staging"]
        assert pool_stage["scheduled"] == job.total
        assert pool_stage["pending"] == 0
        assert pool_stage["errors"] == 0
        ex2.close()
        h2.close()


class _RecordingPrefetcher:
    """Captures the holder's staging order without any device work."""

    def __init__(self):
        self.frags: list = []
        self.throttle_s = None

    def stage(self, frags, throttle_s: float = 0.0) -> StageJob:
        self.frags = list(frags)
        self.throttle_s = throttle_s
        return StageJob(0)


class TestStagingPriorityOrder:
    def test_residency_table_roundtrip(self, tmp_path, fresh_pool):
        holder = _build(str(tmp_path), frames=("f",))
        frags = {
            f.slice: f
            for f in holder.index("i")
            .frame("f")
            .view("standard")
            .fragments()
        }
        # Touch 5 then 3: pool LRU->MRU order becomes [5, 3].
        frags[5].device_plane()
        frags[3].device_plane()
        holder.close()
        keys = Holder(str(tmp_path)).load_residency()
        assert keys == ["i/f/standard/5", "i/f/standard/3"]

    def test_hot_then_residency_mru_then_rest(self, tmp_path, fresh_pool):
        holder = _build(str(tmp_path), frames=("f",))
        frags = {
            f.slice: f
            for f in holder.index("i")
            .frame("f")
            .view("standard")
            .fragments()
        }
        frags[5].device_plane()
        frags[3].device_plane()
        holder.close()

        device_mod._set_pool(PlanePool())
        h2 = Holder(str(tmp_path))
        h2.open()
        rec = _RecordingPrefetcher()
        h2.stage_device_mirrors(
            rec, hot_slices={"i": [7, 2]}, throttle_s=0.125
        )
        order = [f.slice for f in rec.frags]
        assert rec.throttle_s == 0.125
        assert len(order) == N_SLICES
        # Gossip-hot slices first, then the persisted residency table
        # MRU-first (3 was touched last), then the cold tail.
        assert order[:2] == [7, 2]
        assert order[2:4] == [3, 5]
        assert set(order[4:]) == set(range(N_SLICES)) - {7, 2, 3, 5}
        h2.close()

    def test_missing_residency_table_is_fine(self, tmp_path, fresh_pool):
        holder = _build(str(tmp_path), frames=("f",))
        assert Holder(str(tmp_path)).load_residency() == []
        rec = _RecordingPrefetcher()
        holder.stage_device_mirrors(rec)
        assert len(rec.frags) == N_SLICES
        holder.close()


class TestStageErrorAccounting:
    def test_stage_errors_counted_and_surfaced(self, tmp_path, fresh_pool):
        """Staging failures are never just a log line: they count to
        device.stage.errors and the last one surfaces in /debug/hbm."""
        holder = _build(str(tmp_path), frames=("f",))
        frag = holder.index("i").frame("f").view("standard").fragment(0)

        def boom():
            raise RuntimeError("upload exploded")

        frag.device_plane = boom
        pf = Prefetcher(max_workers=1)
        job = pf.stage([frag])
        assert job.wait(timeout=10.0)
        assert job.errors == 1
        snap = device_mod.pool().snapshot()["staging"]
        assert snap["errors"] == 1
        assert "upload exploded" in snap["last_error"]
        holder.close()

    def test_warm_device_mirrors_counts_errors(self, tmp_path, fresh_pool):
        holder = _build(str(tmp_path), frames=("f",))
        frag = holder.index("i").frame("f").view("standard").fragment(0)

        def boom():
            raise RuntimeError("warm exploded")

        frag.device_plane = boom
        warmed = holder.warm_device_mirrors()
        assert warmed == N_SLICES - 1
        snap = device_mod.pool().snapshot()["staging"]
        assert snap["errors"] == 1
        assert "warm exploded" in snap["last_error"]
        holder.close()


class TestStagingOntoMeshShards:
    """ISSUE 12: cold staging must restore every mirror onto the
    slice's OWNING mesh shard (slice mod n_devices), never the default
    device — through both the background staging lane and the eager
    warm path — with the priority order preserved on the multi-device
    (virtual 8-device) mesh."""

    def test_staged_mirrors_land_on_home_shards(self, tmp_path, fresh_pool):
        import jax

        assert len(jax.local_devices()) == 8  # conftest virtual mesh
        holder = _build(str(tmp_path), frames=("f",))
        # Pre-restart: touch two slices so the residency table has an
        # MRU order to replay.
        frags = {
            f.slice: f
            for f in holder.index("i").frame("f").view("standard").fragments()
        }
        frags[5].device_plane()
        frags[3].device_plane()
        holder.close()

        device_mod._set_pool(PlanePool())
        h2 = Holder(str(tmp_path))
        h2.open()
        pf = Prefetcher(max_workers=2)
        job = h2.stage_device_mirrors(pf, hot_slices={"i": [7]})
        assert job.wait(timeout=60.0)
        assert job.snapshot()["errors"] == 0
        for frag in h2.index("i").frame("f").view("standard").fragments():
            mirror = frag._device
            assert mirror is not None, f"slice {frag.slice} not staged"
            (dev,) = mirror.devices()
            assert dev == bp.home_device(frag.slice), (
                f"slice {frag.slice} staged onto {dev}, "
                f"owning shard is {bp.home_device(frag.slice)}"
            )
        # Mirrors are spread across the mesh, not piled on device 0.
        devs = {
            next(iter(f._device.devices()))
            for f in h2.index("i").frame("f").view("standard").fragments()
        }
        assert len(devs) == 8
        h2.close()

    def test_priority_order_preserved_on_mesh(self, tmp_path, fresh_pool):
        holder = _build(str(tmp_path), frames=("f",))
        frags = {
            f.slice: f
            for f in holder.index("i").frame("f").view("standard").fragments()
        }
        frags[6].device_plane()
        frags[1].device_plane()
        holder.close()

        device_mod._set_pool(PlanePool())
        h2 = Holder(str(tmp_path))
        h2.open()
        rec = _RecordingPrefetcher()
        h2.stage_device_mirrors(rec, hot_slices={"i": [4]})
        order = [f.slice for f in rec.frags]
        # Hot, then residency MRU-first, then the tail — the shard
        # placement never reorders the priority queue.
        assert order[:3] == [4, 1, 6]
        h2.close()

    def test_warm_device_mirrors_places_on_home_shards(
        self, tmp_path, fresh_pool
    ):
        holder = _build(str(tmp_path), frames=("f",))
        warmed = holder.warm_device_mirrors()
        assert warmed == N_SLICES
        for frag in holder.index("i").frame("f").view("standard").fragments():
            (dev,) = frag._device.devices()
            assert dev == bp.home_device(frag.slice)
        holder.close()


class TestGossipHotPiggyback:
    def test_hot_field_and_merge_roundtrip(self):
        from pilosa_tpu.cluster.gossip import GossipNodeSet

        a = GossipNodeSet(
            host="127.0.0.1:1",
            bind="127.0.0.1:0",
            hot_provider=lambda: {"i": [4, 1], "j": [0]},
        )
        b = GossipNodeSet(host="127.0.0.1:2", bind="127.0.0.1:0")
        field = a._hot_field()
        assert field == {"hot": {"i": [4, 1], "j": [0]}}
        b._merge_hot("127.0.0.1:1", field)
        assert b.remote_hot_slices() == {"i": [4, 1], "j": [0]}

    def test_merge_hot_ignores_garbage(self):
        from pilosa_tpu.cluster.gossip import GossipNodeSet

        b = GossipNodeSet(host="127.0.0.1:2", bind="127.0.0.1:0")
        b._merge_hot("peer", {"hot": "nope"})
        b._merge_hot("peer", {"hot": {"i": ["x", 3, None]}})
        assert b.remote_hot_slices() == {"i": [3]}

    def test_hot_announcements_expire(self, monkeypatch):
        from pilosa_tpu.cluster import gossip as gossip_mod

        b = gossip_mod.GossipNodeSet(host="127.0.0.1:2", bind="127.0.0.1:0")
        b._merge_hot("peer", {"hot": {"i": [1]}})
        assert b.remote_hot_slices() == {"i": [1]}
        monkeypatch.setattr(gossip_mod, "HOT_TTL_S", -1.0)
        assert b.remote_hot_slices() == {}

    def test_holder_hot_slices_reads_pool_mru(self, tmp_path, fresh_pool):
        holder = _build(str(tmp_path), frames=("f",))
        frags = {
            f.slice: f
            for f in holder.index("i")
            .frame("f")
            .view("standard")
            .fragments()
        }
        frags[2].device_plane()
        frags[8].device_plane()
        hot = holder.hot_slices(limit=2)
        assert hot == {"i": [8, 2]}  # MRU first
        holder.close()
