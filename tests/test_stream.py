"""Streaming data plane: pipe primitives, chunked HTTP bodies end to
end, and incremental map/reduce.

The bounded-memory contract is asserted the way the wire shows it:
responses carry ``Transfer-Encoding: chunked`` and every frame on the
socket is at most the configured chunk size — no large body ever moves
(or is buffered) whole.  The reduce tests pin the executor's
completion-order behavior: a slow node must not delay reducing the
fast nodes' results, and a dead node's slices fail over while the
others are still in flight.
"""

import io
import socket
import tarfile
import threading
import time

import pytest

from pilosa_tpu import stream
from pilosa_tpu.cluster.topology import Cluster, Node
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec.executor import ExecOptions, Executor
from pilosa_tpu.net.client import InternalClient
from pilosa_tpu.net.handler import Handler, Request, make_http_server
from pilosa_tpu.pql.parser import parse_string


# ---------------------------------------------------------------------------
# ChunkPipe / rechunk / IterBody
# ---------------------------------------------------------------------------


class TestChunkPipe:
    def test_roundtrip_chunk_assembly(self):
        pipe = stream.ChunkPipe(capacity=4, chunk_bytes=10)
        pipe.write(b"a" * 7)
        pipe.write(b"b" * 7)  # crosses a chunk boundary
        pipe.write(b"c" * 3)
        pipe.close()
        chunks = list(pipe)
        assert b"".join(chunks) == b"a" * 7 + b"b" * 7 + b"c" * 3
        assert [len(c) for c in chunks[:-1]] == [10]
        assert all(len(c) <= 10 for c in chunks)

    def test_backpressure_blocks_producer(self):
        pipe = stream.ChunkPipe(capacity=2, chunk_bytes=4)
        progressed = []

        def produce():
            for i in range(8):
                pipe.write(b"xxxx")
                progressed.append(i)
            pipe.close()

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        time.sleep(0.1)
        # Capacity 2 + one pending assembly: the producer cannot be done.
        assert len(progressed) < 8
        assert b"".join(pipe) == b"xxxx" * 8
        t.join(timeout=2)
        assert len(progressed) == 8

    def test_abort_unblocks_producer(self):
        pipe = stream.ChunkPipe(capacity=1, chunk_bytes=4)
        state = {}

        def produce():
            try:
                for _ in range(100):
                    pipe.write(b"xxxx")
            except stream.PipeAbortedError:
                state["aborted"] = True

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        time.sleep(0.05)
        pipe.abort()
        t.join(timeout=2)
        assert state.get("aborted") is True

    def test_producer_error_reraises_on_consumer(self):
        def boom(w):
            w.write(b"partial")
            raise RuntimeError("producer died")

        gen = stream.generate_from_writer(boom, chunk_bytes=4)
        with pytest.raises(RuntimeError, match="producer died"):
            list(gen)

    def test_generator_close_stops_producer(self):
        done = threading.Event()

        def produce(w):
            try:
                while True:
                    w.write(b"x" * 64)
            finally:
                done.set()

        gen = stream.generate_from_writer(produce, capacity=2, chunk_bytes=64)
        next(gen)
        gen.close()
        assert done.wait(timeout=2)


class TestRechunk:
    def test_constant_chunks(self):
        out = list(stream.rechunk([b"ab", b"cdefg", b"", b"hij"], 4))
        assert out == [b"abcd", b"efgh", b"ij"]

    def test_iter_body_close_reaches_generator(self):
        closed = []

        def gen():
            try:
                yield b"x" * 100
            finally:
                closed.append(True)

        body = stream.IterBody(gen(), chunk_bytes=16)
        it = iter(body)
        assert len(next(it)) == 16
        body.close()
        assert closed == [True]

    def test_batched(self):
        assert list(stream.batched(range(5), 2)) == [[0, 1], [2, 3], [4]]
        assert list(stream.batched([], 3)) == []


# ---------------------------------------------------------------------------
# chunked request/response bodies over a real server
# ---------------------------------------------------------------------------

CHUNK = 512  # small so modest fixtures produce many frames


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "h"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def http_server(holder):
    cluster = Cluster()
    handler = Handler(holder=holder, cluster=cluster, stream_chunk_bytes=CHUNK)
    srv = make_http_server(handler, "127.0.0.1", 0)
    cluster.add_node(f"127.0.0.1:{srv.server_address[1]}")
    executor = Executor(
        holder=holder, host=f"127.0.0.1:{srv.server_address[1]}", cluster=cluster
    )
    handler.executor = executor
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    executor.close()
    srv.shutdown()
    srv.server_close()


def _populated_fragment(holder, n_bits=3000):
    idx = holder.create_index("i")
    idx.create_frame("f")
    f = holder.frame("i", "f")
    for col in range(n_bits):
        f.set_bit(VIEW_STANDARD, col % 7, col)
    return holder.fragment("i", "f", VIEW_STANDARD, 0)


def _raw_chunked_get(addr, target, accept):
    """Issue a GET and parse the raw chunked framing off the socket —
    asserting what actually moves on the wire, not what http.client
    reassembles."""
    host, port = addr
    s = socket.create_connection((host, port), timeout=10)
    try:
        s.sendall(
            f"GET {target} HTTP/1.1\r\nHost: {host}\r\n"
            f"Accept: {accept}\r\nConnection: close\r\n\r\n".encode()
        )
        fp = s.makefile("rb")
        status_line = fp.readline()
        headers = {}
        while True:
            line = fp.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        frames = []
        if headers.get("transfer-encoding") == "chunked":
            while True:
                size = int(fp.readline().split(b";")[0], 16)
                if size == 0:
                    fp.readline()
                    break
                data = fp.read(size)
                fp.read(2)  # CRLF
                frames.append(data)
        return status_line, headers, frames
    finally:
        s.close()


class TestChunkedExport:
    def test_export_is_chunked_with_constant_size_writes(self, holder, http_server):
        frag = _populated_fragment(holder)
        assert frag is not None
        status_line, headers, frames = _raw_chunked_get(
            http_server.server_address,
            "/export?index=i&frame=f&view=standard&slice=0",
            "text/csv",
        )
        assert b"200" in status_line
        assert headers.get("transfer-encoding") == "chunked"
        assert "content-length" not in headers
        # Constant-size writes: every frame except the tail is exactly
        # the configured chunk size, and none exceeds it.
        assert len(frames) > 2
        assert all(len(f) == CHUNK for f in frames[:-1])
        assert len(frames[-1]) <= CHUNK
        body = b"".join(frames)
        assert body == b"".join(frag.csv_chunks())

    def test_fragment_data_is_chunked_and_restorable(self, holder, http_server):
        _populated_fragment(holder)
        _, headers, frames = _raw_chunked_get(
            http_server.server_address,
            "/fragment/data?index=i&frame=f&view=standard&slice=0",
            "*/*",
        )
        assert headers.get("transfer-encoding") == "chunked"
        assert all(len(f) <= CHUNK for f in frames)
        # The reassembled stream is a valid fragment archive.
        tr = tarfile.open(fileobj=io.BytesIO(b"".join(frames)), mode="r|")
        assert sorted(m.name for m in tr) == ["cache", "checksum", "data"]

    def test_chunked_post_restore_roundtrip(self, holder, http_server):
        """Client restore streams the archive as a chunked request body;
        the handler applies it off the stream."""
        frag = _populated_fragment(holder, n_bits=500)
        client = InternalClient(
            "%s:%d" % http_server.server_address, timeout=10.0
        )
        archive = b"".join(frag.tar_chunks(chunk_bytes=CHUNK))
        before = sorted(frag.row(0).bits())
        # Wipe, then restore through the chunked POST path.
        for col in before:
            frag.clear_bit(0, col)
        assert frag.row(0).bits() == []
        client.restore_slice_from(
            "i", "f", VIEW_STANDARD, 0, io.BytesIO(archive)
        )
        frag2 = holder.fragment("i", "f", VIEW_STANDARD, 0)
        assert sorted(frag2.row(0).bits()) == before

    def test_export_client_streams_constant_chunks(self, holder, http_server):
        frag = _populated_fragment(holder)
        client = InternalClient(
            "%s:%d" % http_server.server_address, timeout=10.0
        )
        client.chunk_bytes = CHUNK

        class CountingWriter:
            def __init__(self):
                self.sizes = []
                self.buf = []

            def write(self, b):
                self.sizes.append(len(b))
                self.buf.append(b)

        w = CountingWriter()
        client.export_to(w, "i", "f", "standard", 0)
        assert b"".join(w.buf) == b"".join(frag.csv_chunks())
        # The client moved the body in bounded reads, never whole.
        assert max(w.sizes) <= CHUNK


class TestStreamOpenRetry:
    def test_retries_then_succeeds(self):
        calls = []

        def open_fn():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionRefusedError("nope")
            return "ok"

        assert (
            stream.open_with_retry(open_fn, attempts=3, backoff=0.001) == "ok"
        )
        assert len(calls) == 3

    def test_exhausted_raises_last(self):
        def open_fn():
            raise ConnectionRefusedError("always")

        with pytest.raises(ConnectionRefusedError):
            stream.open_with_retry(open_fn, attempts=2, backoff=0.001)


# ---------------------------------------------------------------------------
# incremental map/reduce with eager failover
# ---------------------------------------------------------------------------


def _two_node_cluster():
    c = Cluster(nodes=[Node(host="local:1"), Node(host="remote:2")])
    return c


class SlowClient:
    """Remote leg that parks until released (or a deadline)."""

    def __init__(self, delay):
        self.delay = delay

    def execute_query(self, index, query, slices, remote):
        time.sleep(self.delay)
        return [len(slices or [])]


class TestIncrementalReduce:
    def _executor(self, holder, cluster, client):
        return Executor(
            holder,
            host=cluster.nodes[0].host,
            cluster=cluster,
            client_factory=lambda node: client,
        )

    def test_slow_node_does_not_delay_fast_reduction(self, holder):
        """The local node's result must reduce while the slow remote is
        still in flight (as_completed semantics), not after a barrier
        on all futures."""
        c = _two_node_cluster()
        holder.create_index("i").create_frame("f")
        e = self._executor(holder, c, SlowClient(delay=1.0))
        slices = list(range(8))
        local = [s for s in slices if c.fragment_nodes("i", s)[0].host == e.host]
        remote = [s for s in slices if s not in local]
        assert local and remote  # both nodes own work

        t0 = time.monotonic()
        reduce_times = []

        def map_fn(node_slices):
            return len(node_slices)

        def reduce_fn(acc, x):
            reduce_times.append(time.monotonic() - t0)
            return (acc or 0) + x

        call = parse_string('Count(Bitmap(rowID=0, frame="f"))').calls[0]
        total = e._map_reduce("i", slices, call, ExecOptions(), map_fn, reduce_fn)
        e.close()
        assert total == len(slices)
        assert len(reduce_times) == 2
        # First reduction (the local mapper) lands well before the slow
        # remote's 1 s sleep elapses; the last waits for it.
        assert reduce_times[0] < 0.5
        assert reduce_times[-1] >= 0.9

    def test_eager_failover_on_node_error(self, holder):
        """A dead node's slices resubmit to replicas immediately and the
        query still answers completely.  Host-only mapper: this drives
        the _map_reduce control flow, not device compute (the full
        device path is covered by test_executor's failover tests)."""
        c = _two_node_cluster()
        c.replica_n = 2  # both nodes own every slice
        holder.create_index("i").create_frame("f")

        class DeadClient:
            def execute_query(self, index, query, slices, remote):
                raise ConnectionError("remote down")

        e = self._executor(holder, c, DeadClient())
        slices = list(range(6))

        def map_fn(node_slices):
            return len(node_slices)

        def reduce_fn(acc, x):
            return (acc or 0) + x

        call = parse_string('Count(Bitmap(rowID=0, frame="f"))').calls[0]
        total = e._map_reduce("i", slices, call, ExecOptions(), map_fn, reduce_fn)
        e.close()
        # Every slice answered exactly once — the dead node's share via
        # immediate replica failover onto the local node.
        assert total == len(slices)


# ---------------------------------------------------------------------------
# Request body streaming plumbing
# ---------------------------------------------------------------------------


class TestRequestBody:
    def test_read_body_materializes_stream(self):
        req = Request(method="POST", path="/x", stream=io.BytesIO(b"payload"))
        assert req.read_body() == b"payload"
        assert req.stream is None
        assert req.body == b"payload"

    def test_body_reader_prefers_stream(self):
        req = Request(method="POST", path="/x", stream=io.BytesIO(b"abc"))
        assert req.body_reader().read() == b"abc"

    def test_chunked_body_reader_decodes_frames(self):
        wire = b"4\r\nWiki\r\n5\r\npedia\r\n0\r\n\r\n"
        r = stream.ChunkedBodyReader(io.BytesIO(wire))
        assert r.read(6) == b"Wikipe"
        assert r.read() == b"dia"
        assert r.read(10) == b""

    def test_length_body_reader_bounds(self):
        r = stream.LengthBodyReader(io.BytesIO(b"0123456789"), 4)
        assert r.read() == b"0123"
        assert r.read(1) == b""
        assert r.drain() is True
