"""Observability: query tracing, /metrics exposition, slow-query log,
and the stats-client satellites (tag union, close/clamp, percentiles,
failure isolation)."""

import json
import re
import socket
import time

import pytest

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.topology import Cluster
from pilosa_tpu.net.client import InternalClient
from pilosa_tpu.net.handler import Handler, Request
from pilosa_tpu.net.server import Server
from pilosa_tpu.obs import prom, trace
from pilosa_tpu.obs import stats as stats_mod
from pilosa_tpu.ops.bitplane import SLICE_WIDTH


# ---------------------------------------------------------------------------
# tracer unit tests
# ---------------------------------------------------------------------------


class TestTracer:
    def test_span_tree_and_ring(self):
        tr = trace.Tracer(capacity=4)
        root = tr.start_trace("query", index="i")
        token = root.activate()
        with tr.span("parse"):
            pass
        with tr.span("execute"):
            with tr.span("plan", slices=3):
                pass
        root.deactivate(token)
        rec = tr.finish_root(root)
        assert rec["trace_id"] == root.trace_id
        names = [s["name"] for s in rec["spans"]]
        assert names[0] == "query"
        assert set(names) == {"query", "parse", "execute", "plan"}
        by_name = {s["name"]: s for s in rec["spans"]}
        assert by_name["parse"]["parent_id"] == root.span_id
        assert by_name["plan"]["parent_id"] == by_name["execute"]["span_id"]
        assert by_name["plan"]["tags"]["slices"] == 3
        assert all(s["duration_ms"] is not None for s in rec["spans"])
        assert tr.traces() == [rec]

    def test_ring_capacity_and_min_ms(self):
        tr = trace.Tracer(capacity=2)
        for i in range(3):
            tr.finish_root(tr.start_trace(f"q{i}"))
        got = tr.traces()
        assert [t["name"] for t in got] == ["q1", "q2"]
        assert tr.traces(min_ms=1e9) == []

    def test_absorb_remote_spans(self):
        tr = trace.Tracer()
        root = tr.start_trace("query")
        payload = json.dumps(
            {
                "trace_id": root.trace_id,
                "spans": [
                    {
                        "name": "query",
                        "span_id": "abc",
                        "parent_id": root.span_id,
                        "start": time.time(),
                        "duration_ms": 1.5,
                        "tags": {"node": "remote:1"},
                    }
                ],
            }
        )
        tr.absorb(payload)
        rec = tr.finish_root(root)
        remote = [s for s in rec["spans"] if s["span_id"] == "abc"]
        assert remote and remote[0]["tags"]["node"] == "remote:1"
        # Garbage payloads are ignored, never raise.
        tr.absorb("not json")
        tr.absorb('{"no": "trace_id"}')

    def test_propagated_trace_continues_ids(self):
        tr = trace.Tracer()
        root = tr.start_trace("query", trace_id="t" * 32, parent_span_id="p" * 16)
        assert root.trace_id == "t" * 32
        assert root.parent_id == "p" * 16
        rec = tr.finish_root(root)
        assert rec["trace_id"] == "t" * 32

    def test_stage_breakdown_excludes_root(self):
        tr = trace.Tracer()
        root = tr.start_trace("query")
        token = root.activate()
        with tr.span("parse"):
            pass
        with tr.span("parse"):
            pass
        root.deactivate(token)
        rec = tr.finish_root(root)
        stages = trace.stage_breakdown(rec)
        assert set(stages) == {"parse"}
        assert stages["parse"] >= 0

    def test_error_annotation(self):
        tr = trace.Tracer()
        root = tr.start_trace("query")
        token = root.activate()
        with pytest.raises(ValueError):
            with tr.span("execute"):
                raise ValueError("boom")
        root.deactivate(token)
        rec = tr.finish_root(root)
        ex = [s for s in rec["spans"] if s["name"] == "execute"][0]
        assert ex["tags"]["error"] == "ValueError"

    def test_nop_tracer(self):
        tr = trace.NOP_TRACER
        root = tr.start_trace("query")
        with tr.span("x", anything=1) as sp:
            sp.annotate(more=2)
        assert tr.finish_root(root) is None
        assert tr.traces() == []
        assert tr.remote_headers(root) == {}


# ---------------------------------------------------------------------------
# stats satellites
# ---------------------------------------------------------------------------


class TestStatsSatellites:
    def test_multi_tags_union(self):
        a = stats_mod.ExpvarStatsClient().with_tags("index:i")
        b = stats_mod.ExpvarStatsClient().with_tags("frame:f", "index:i")
        m = stats_mod.MultiStatsClient([a, b])
        assert m.tags() == ["frame:f", "index:i"]
        assert stats_mod.MultiStatsClient([]).tags() == []

    def test_percentiles_interpolated(self):
        c = stats_mod.ExpvarStatsClient()
        for v in (1.0, 2.0, 3.0, 4.0):
            c.histogram("lat", v)
        h = c.snapshot()["histograms"]["lat"]
        assert h["p50"] == pytest.approx(2.5)
        assert h["p90"] == pytest.approx(3.7)
        assert h["p99"] == pytest.approx(3.97)
        assert h["p999"] == pytest.approx(3.997)
        # Single sample: every quantile is the sample.
        c.histogram("one", 7.0)
        h1 = c.snapshot()["histograms"]["one"]
        assert h1["p50"] == h1["p999"] == 7.0

    def test_statsd_close_releases_socket(self):
        c = stats_mod.StatsDClient("127.0.0.1:19999")
        child = c.with_tags("index:i")
        c.close()
        # Closed socket: sends are swallowed (fire-and-forget), and the
        # shared child socket is released too.
        c.count("x")
        child.count("y")
        assert c._sock.fileno() == -1

    def test_multi_close_fans_out(self):
        closed = []

        class Rec:
            def close(self):
                closed.append(True)

        stats_mod.MultiStatsClient([Rec(), Rec()]).close()
        assert len(closed) == 2

    def test_statsd_datagram_clamped(self):
        rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(2.0)
        port = rx.getsockname()[1]
        huge_tags = [f"tag{i}:{'v' * 50}" for i in range(60)]
        c = stats_mod.StatsDClient(f"127.0.0.1:{port}").with_tags(*huge_tags)
        c.count("bits", 1)
        data, _ = rx.recvfrom(65536)
        assert len(data) <= stats_mod.StatsDClient.MAX_PAYLOAD
        # Oversize drops the tag suffix, keeping the metric parseable.
        assert data.startswith(b"pilosa.bits:1|c")
        rx.close()
        c.close()

    def test_raising_stats_never_drops_response(self):
        class Raising:
            def histogram(self, name, value):
                raise RuntimeError("stats backend down")

            def count(self, name, value=1):
                raise RuntimeError("stats backend down")

        h = Handler(stats=Raising())
        resp = h.dispatch(Request(method="GET", path="/version"))
        assert resp.status == 200
        assert b"version" in resp.body


# ---------------------------------------------------------------------------
# prometheus exposition
# ---------------------------------------------------------------------------

# Label VALUES may legally contain braces (the http latency family
# labels routes by template, e.g. path="/index/{index}/query"), so the
# label block matches greedily to the last "}".
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? [-+0-9.einfa]+$"
)


def _assert_valid_exposition(text: str) -> None:
    assert text.endswith("\n")
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert parts[3] in (
                "counter", "gauge", "summary", "histogram"
            ), line
        else:
            assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"


class TestProm:
    def test_render_kinds_and_labels(self):
        c = stats_mod.ExpvarStatsClient()
        c.with_tags("index:i", "frame:f").count("setBit", 3)
        c.gauge("rows", 7.0)
        for v in (1.0, 2.0, 3.0, 4.0):
            c.histogram("lat", v)
        text = prom.render(c.snapshot(), extra_gauges={"uptime_seconds": 12.5})
        _assert_valid_exposition(text)
        assert '# TYPE pilosa_setBit_total counter' in text
        assert 'pilosa_setBit_total{frame="f",index="i"} 3' in text
        assert "# TYPE pilosa_rows gauge" in text
        assert "pilosa_rows 7" in text
        assert "# TYPE pilosa_lat summary" in text
        assert 'pilosa_lat{quantile="0.5"} 2.5' in text
        assert "pilosa_lat_sum 10" in text
        assert "pilosa_lat_count 4" in text
        assert "pilosa_uptime_seconds 12.5" in text

    def test_name_sanitization(self):
        text = prom.render({"counts": {"http.POST./index/i/query": 2}})
        _assert_valid_exposition(text)
        assert "pilosa_http_POST__index_i_query_total 2" in text

    def test_empty_snapshot(self):
        assert prom.render({}) == ""
        _assert_valid_exposition(prom.render({}, extra_gauges={"threads": 3}))


# ---------------------------------------------------------------------------
# single-node integration: /metrics, /debug/traces, slow-query log
# ---------------------------------------------------------------------------


@pytest.fixture
def obs_server(tmp_path):
    logs = []
    s = Server(
        data_dir=str(tmp_path / "data"),
        stats=stats_mod.ExpvarStatsClient(),
        logger=logs.append,
        slow_query_ms=0.0001,  # every query is "slow"
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
    )
    s.open()
    yield s, logs
    s.close()


class TestObsEndpoints:
    def _populate(self, s):
        s.holder.create_index_if_not_exists("i")
        f = s.holder.index("i").create_frame_if_not_exists("f")
        f.set_bit("standard", 1, 5)
        f.set_bit("standard", 1, 9)

    def test_metrics_exposition(self, obs_server):
        s, _ = obs_server
        self._populate(s)
        c = InternalClient(s.host, timeout=10.0)
        assert c.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == 2
        status, data, headers = c._request_meta("GET", "/metrics")
        assert status == 200
        assert headers["content-type"].startswith("text/plain")
        text = data.decode()
        _assert_valid_exposition(text)
        # Fragment write counter with hierarchical labels.
        assert re.search(
            r'pilosa_setBit_total\{[^}]*index="i"[^}]*\} 2', text
        ), text
        # Per-call query counter from the executor.
        assert 'pilosa_Count_total{index="i"} 1' in text
        # Handler latency summary and process gauges.
        assert "# TYPE pilosa_uptime_seconds gauge" in text

    def test_trace_ring_and_min_ms_filter(self, obs_server):
        s, _ = obs_server
        self._populate(s)
        c = InternalClient(s.host, timeout=10.0)
        c.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))')
        status, data = c._request("GET", "/debug/traces")
        traces = json.loads(data)["traces"]
        assert status == 200 and traces
        t = traces[-1]
        names = {sp["name"] for sp in t["spans"]}
        assert {"query", "parse", "execute", "call.Count", "plan"} <= names
        assert t["spans"][0]["tags"]["query"].startswith("Count(")
        # min_ms far above any query filters everything out.
        status, data = c._request(
            "GET", "/debug/traces", query={"min_ms": "1000000"}
        )
        assert json.loads(data)["traces"] == []
        # invalid filter is a 400, not a 500
        status, _ = c._request("GET", "/debug/traces", query={"min_ms": "x"})
        assert status == 400

    def test_slow_query_log_exactly_one_line(self, obs_server):
        s, logs = obs_server
        self._populate(s)
        c = InternalClient(s.host, timeout=10.0)
        c.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))')
        slow = [m for m in logs if m.startswith("slow query ")]
        assert len(slow) == 1, slow
        payload = json.loads(slow[0][len("slow query "):])
        assert payload["index"] == "i"
        assert payload["query"].startswith("Count(")
        assert payload["ms"] >= 0.0001
        assert payload["trace_id"]
        assert "parse" in payload["stages"] and "execute" in payload["stages"]

    def test_slow_query_log_disabled_by_default(self, tmp_path):
        logs = []
        s = Server(
            data_dir=str(tmp_path / "d2"),
            logger=logs.append,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
        )
        s.open()
        try:
            self._populate(s)
            c = InternalClient(s.host, timeout=10.0)
            c.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))')
            assert not [m for m in logs if m.startswith("slow query ")]
        finally:
            s.close()

    def test_cache_counters_surface(self, obs_server):
        s, _ = obs_server
        self._populate(s)
        c = InternalClient(s.host, timeout=10.0)
        # Explicit-ids TopN resolves counts through the ranked cache
        # (fragment._row_count_locked), exercising hit (row 1) and miss
        # (row 99) counters.
        c.execute_pql("i", 'TopN(frame="f", n=2, ids=[1, 99])')
        snap = s.stats.snapshot()
        assert any(k.startswith("cacheHit") or k.startswith("cacheMiss")
                   for k in snap["counts"]), snap["counts"]


# ---------------------------------------------------------------------------
# multi-node: one trace spans the HTTP fan-out
# ---------------------------------------------------------------------------


@pytest.fixture
def two_obs_servers(tmp_path):
    recv0, recv1 = bc.HTTPBroadcastReceiver(), bc.HTTPBroadcastReceiver()
    b0, b1 = bc.HTTPBroadcaster([]), bc.HTTPBroadcaster([])
    cluster0, cluster1 = Cluster(replica_n=1), Cluster(replica_n=1)
    servers = []
    for i, (cl, br, rc) in enumerate(
        ((cluster0, b0, recv0), (cluster1, b1, recv1))
    ):
        servers.append(
            Server(
                data_dir=str(tmp_path / f"n{i}"),
                cluster=cl,
                broadcaster=br,
                broadcast_receiver=rc,
                stats=stats_mod.ExpvarStatsClient(),
                anti_entropy_interval=3600,
                polling_interval=3600,
                cache_flush_interval=3600,
            )
        )
    s0, s1 = servers
    s0.open()
    s1.open()
    b0.internal_hosts.append(recv1.bound_host)
    b1.internal_hosts.append(recv0.bound_host)
    for c in (cluster0, cluster1):
        for host in sorted([s0.host, s1.host]):
            if c.node_by_host(host) is None:
                c.add_node(host)
        c.nodes.sort(key=lambda n: n.host)
    yield s0, s1
    s0.close()
    s1.close()


class TestDistributedTrace:
    def test_single_trace_covers_remote_fanout(self, two_obs_servers):
        s0, s1 = two_obs_servers
        for s in (s0, s1):
            s.holder.create_index_if_not_exists("i")
            s.holder.index("i").create_frame_if_not_exists("f")
        c0 = InternalClient(s0.host, timeout=10.0)
        n_slices = 6
        for sl in range(n_slices):
            c0.execute_query(
                "i", f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH})'
            )
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if (
                s0.holder.index("i").max_slice() == n_slices - 1
                and s1.holder.index("i").max_slice() == n_slices - 1
            ):
                break
            time.sleep(0.02)
        assert (
            c0.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))')
            == n_slices
        )

        # The coordinator retains ONE trace for the count query whose
        # spans cover parse, plan, local slice execution, AND the remote
        # node's leg (absorbed across the HTTP hop via X-Trace-Id).
        status, data = c0._request("GET", "/debug/traces")
        assert status == 200
        counts = [
            t
            for t in json.loads(data)["traces"]
            if t["spans"][0]["tags"].get("query", "").startswith("Count(")
        ]
        assert len(counts) == 1
        t = counts[0]
        names = {sp["name"] for sp in t["spans"]}
        assert {"parse", "execute", "call.Count", "plan", "map.local",
                "rpc.execute"} <= names
        # Fused device execution appears as "coalesce" when the server's
        # [exec] coalescing scheduler (the default) carries the launch,
        # "exec.device" on the direct path.
        assert names & {"coalesce", "exec.device"}
        assert all(
            sp.get("duration_ms") is not None for sp in t["spans"]
        )

        by_id = {sp["span_id"]: sp for sp in t["spans"]}
        rpc = [sp for sp in t["spans"] if sp["name"] == "rpc.execute"]
        assert rpc and rpc[0]["tags"]["node"] == s1.host
        # The remote leg's root span came back across the hop: a "query"
        # span tagged with the remote node, parented under the rpc span.
        remote_roots = [
            sp
            for sp in t["spans"]
            if sp["name"] == "query" and sp["tags"].get("node") == s1.host
        ]
        assert remote_roots
        assert remote_roots[0]["parent_id"] in {r["span_id"] for r in rpc}
        # Remote-side execution spans rode along too.
        remote_ids = {remote_roots[0]["span_id"]}
        for sp in t["spans"]:
            if sp["parent_id"] in remote_ids:
                remote_ids.add(sp["span_id"])
        assert any(
            by_id[i]["name"] == "execute" for i in remote_ids if i in by_id
        )

        # The remote node independently retained its leg under the SAME
        # trace id (linked via the propagated X-Trace-Id).
        c1 = InternalClient(s1.host, timeout=10.0)
        _, data1 = c1._request("GET", "/debug/traces")
        remote_trace_ids = {
            tt["trace_id"] for tt in json.loads(data1)["traces"]
        }
        assert t["trace_id"] in remote_trace_ids

        # /metrics on the coordinator includes fragment + query counters.
        status, data, _ = c0._request_meta("GET", "/metrics")
        text = data.decode()
        _assert_valid_exposition(text)
        assert "pilosa_setBit_total" in text
        assert 'pilosa_Count_total{index="i"} 1' in text
