"""Fragment tests — the component tier, mirroring fragment_test.go's
wrapper pattern: temp-dir fixture + reopen for persistence checks."""

import io
import os

import numpy as np
import pytest

from pilosa_tpu.core import cache as cm
from pilosa_tpu.core.bitmap import RowBitmap
from pilosa_tpu.core.fragment import (
    Fragment,
    FragmentError,
    PairSet,
    TopOptions,
)
from pilosa_tpu.core.attr import AttrStore
from pilosa_tpu.ops import bitplane as bp

SW = bp.SLICE_WIDTH


@pytest.fixture
def frag(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    yield f
    f.close()


def reopen(f: Fragment) -> Fragment:
    f.close()
    f2 = Fragment(
        f.path, f.index, f.frame, f.view, f.slice,
        cache_type=f.cache_type, cache_size=f.cache_size, max_op_n=f.max_op_n,
    )
    f2.open()
    return f2


def test_set_clear_contains(frag):
    assert frag.set_bit(2, 100)
    assert not frag.set_bit(2, 100)
    assert frag.contains(2, 100)
    assert frag.row(2).bits() == [100]
    assert frag.clear_bit(2, 100)
    assert not frag.contains(2, 100)


def test_column_out_of_bounds(tmp_path):
    f = Fragment(str(tmp_path / "3"), "i", "f", "standard", 3)
    f.open()
    with pytest.raises(FragmentError):
        f.set_bit(0, 5)  # col 5 is in slice 0, not 3
    f.set_bit(0, 3 * SW + 5)
    assert f.row(0).bits() == [3 * SW + 5]
    f.close()


def test_persistence_via_oplog(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    f.set_bit(1, 10)
    f.set_bit(1, 20)
    f.set_bit(130, 5)
    f.clear_bit(1, 10)
    f2 = reopen(f)
    assert f2.row(1).bits() == [20]
    assert f2.row(130).bits() == [5]
    assert f2.max_row_id == 130
    f2.close()


def test_snapshot_on_max_opn(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0, max_op_n=5)
    f.open()
    for i in range(6):
        f.set_bit(0, i)
    assert f._op_n < 5  # snapshot reset the op counter
    f2 = reopen(f)
    assert f2.row(0).bits() == [0, 1, 2, 3, 4, 5]
    f2.close()


def test_import_bulk_and_row_counts(frag):
    rows = [0, 0, 1, 2, 2, 2]
    cols = [1, 2, 3, 4, 5, 6]
    frag.import_bulk(rows, cols)
    assert frag.row(0).bits() == [1, 2]
    assert frag.row(2).bits() == [4, 5, 6]
    assert frag.cache.get(2) == 3
    f2 = reopen(frag)
    assert f2.row(2).bits() == [4, 5, 6]
    f2.close()


def test_count(frag):
    frag.import_bulk([0, 1, 5], [1, 2, 3])
    assert frag.count() == 3


def test_top_n_basic(frag):
    frag.import_bulk(
        [0, 0, 0, 1, 1, 2], [1, 2, 3, 4, 5, 6],
    )
    top = frag.top(TopOptions(n=2))
    assert [(p.id, p.count) for p in top] == [(0, 3), (1, 2)]
    top_all = frag.top(TopOptions())
    assert [(p.id, p.count) for p in top_all] == [(0, 3), (1, 2), (2, 1)]


def test_top_with_src_intersection(frag):
    frag.import_bulk(
        [0, 0, 0, 1, 1, 2], [10, 20, 30, 10, 40, 50],
    )
    src = RowBitmap.from_bits([10, 40])
    top = frag.top(TopOptions(n=10, src=src))
    assert [(p.id, p.count) for p in top] == [(1, 2), (0, 1)]


def test_top_row_ids(frag):
    frag.import_bulk([0, 1, 1, 2, 2, 2], [1, 2, 3, 4, 5, 6])
    top = frag.top(TopOptions(row_ids=[0, 2]))
    assert [(p.id, p.count) for p in top] == [(2, 3), (0, 1)]


def test_top_min_threshold(frag):
    frag.import_bulk([0, 1, 1, 2, 2, 2], [1, 2, 3, 4, 5, 6])
    top = frag.top(TopOptions(min_threshold=2))
    assert [(p.id, p.count) for p in top] == [(2, 3), (1, 2)]


def test_top_filters_via_attr_store(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    store = AttrStore(str(tmp_path / "attrs"))
    store.open()
    f.row_attr_store = store
    f.import_bulk([0, 0, 1, 2], [1, 2, 3, 4])
    store.set_attrs(0, {"category": "a"})
    store.set_attrs(1, {"category": "b"})
    top = f.top(TopOptions(filter_field="category", filter_values=["b"]))
    assert [(p.id, p.count) for p in top] == [(1, 1)]
    top = f.top(TopOptions(filter_field="category", filter_values=["a", "b"]))
    assert [(p.id, p.count) for p in top] == [(0, 2), (1, 1)]
    store.close()
    f.close()


def test_top_tanimoto(frag):
    # reference semantics: score = ceil(100*|A&B| / (|A|+|B|-|A&B|)) > thr
    frag.import_bulk(
        [0, 0, 0, 1, 1, 2, 2, 2, 2], [1, 2, 3, 1, 2, 1, 2, 3, 4],
    )
    src = RowBitmap.from_bits([1, 2, 3])
    top = frag.top(TopOptions(src=src, tanimoto_threshold=70))
    got = {p.id: p.count for p in top}
    # row0: |A&B|=3, |A|=3 -> 100 > 70 yes; row1: 2/(2+3-2)=67 no;
    # row2: 3/(4+3-3)=75 > 70 yes
    assert got == {0: 3, 2: 3}


def test_blocks_checksums_change(frag):
    assert frag.blocks() == []
    frag.set_bit(0, 1)
    b1 = frag.blocks()
    assert [b[0] for b in b1] == [0]
    frag.set_bit(150, 1)  # second block
    b2 = frag.blocks()
    assert [b[0] for b in b2] == [0, 1]
    frag.set_bit(0, 2)
    b3 = frag.blocks()
    assert b3[0][1] != b2[0][1]  # block 0 checksum changed
    assert b3[1][1] == b2[1][1]  # block 1 untouched
    assert frag.checksum() != b""


def test_blocks_cached_until_write(frag, monkeypatch):
    """blocks() on an unmodified fragment re-hashes nothing; a write
    re-hashes only the touched block (VERDICT r1: the reference caches
    block checksums and invalidates per-write, fragment.go:717-796)."""
    frag.set_bit(0, 1)
    frag.set_bit(150, 2)  # block 1
    b1 = frag.blocks()

    computed = []
    orig = Fragment._block_positions

    def spy(self, block_id, rows=None):
        computed.append(block_id)
        return orig(self, block_id, rows)

    monkeypatch.setattr(Fragment, "_block_positions", spy)
    assert frag.blocks() == b1
    assert computed == []  # fully served from cache

    frag.set_bit(160, 3)  # dirty block 1 only
    b2 = frag.blocks()
    assert computed == [1]
    assert b2[0] == b1[0]
    assert b2[1] != b1[1]

    # clear_bit dirties too; unchanged no-op writes don't
    frag.clear_bit(160, 3)
    assert frag.blocks() == b1
    assert computed == [1, 1]
    frag.clear_bit(160, 3)  # already clear: no change, no re-hash
    assert frag.blocks() == b1
    assert computed == [1, 1]

    # import_bulk dirties every touched block
    frag.import_bulk([0, 205], [7, 8])
    frag.blocks()
    assert sorted(computed[2:]) == [0, 2]


def test_blocks_cache_reset_on_reopen(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    f.set_bit(3, 4)
    want = f.blocks()
    f2 = reopen(f)
    try:
        assert f2.blocks() == want
    finally:
        f2.close()


def test_block_data(frag):
    frag.set_bit(0, 5)
    frag.set_bit(102, 9)
    ps = frag.block_data(1)
    assert ps.row_ids == [102]
    assert ps.column_ids == [9]


def test_merge_block_consensus(frag):
    # local has {r0c1}; two remotes have {r0c1, r0c2}; majority = 2 of 3
    frag.set_bit(0, 1)
    remote = PairSet(row_ids=[0, 0], column_ids=[1, 2])
    sets, clears = frag.merge_block(0, [remote, remote])
    # consensus: c1 (3 votes), c2 (2 votes >= 2) -> local gains c2
    assert frag.row(0).bits() == [1, 2]
    # remotes already have both; no diffs for them
    assert all(not s.row_ids for s in sets)
    assert all(not c.row_ids for c in clears)


def test_merge_block_clears_minority_bit(frag):
    # local has a bit nobody else has; 1 of 3 votes < 2 -> cleared
    frag.set_bit(0, 7)
    empty = PairSet()
    sets, clears = frag.merge_block(0, [empty, empty])
    assert frag.row(0).bits() == []
    assert all(not s.row_ids for s in sets)


def test_merge_block_tie_sets(frag):
    # local empty, one remote has the bit: 1 of 2 votes, majority=(2+1)//2=1
    # -> tie resolves to set (reference: "even split then a set is used")
    remote = PairSet(row_ids=[0], column_ids=[3])
    sets, clears = frag.merge_block(0, [remote])
    assert frag.row(0).bits() == [3]
    assert not sets[0].row_ids and not clears[0].row_ids


def test_merge_block_remote_diffs(frag):
    # local + remote1 have c1 (2/3 majority); remote2 lacks it -> remote2
    # gets a set-diff
    frag.set_bit(0, 1)
    r1 = PairSet(row_ids=[0], column_ids=[1])
    r2 = PairSet()
    sets, clears = frag.merge_block(0, [r1, r2])
    assert not sets[0].row_ids
    assert sets[1].row_ids == [0] and sets[1].column_ids == [1]


def test_tar_roundtrip(tmp_path, frag):
    frag.import_bulk([0, 1, 250], [1, 2, 3])
    buf = io.BytesIO()
    frag.write_to(buf)
    buf.seek(0)
    f2 = Fragment(str(tmp_path / "copy"), "i", "f", "standard", 0)
    f2.open()
    f2.read_from(buf)
    assert f2.row(0).bits() == [1]
    assert f2.row(250).bits() == [3]
    assert f2.max_row_id == 250
    # restored fragment persisted to its own file
    f3 = reopen(f2)
    assert f3.row(250).bits() == [3]
    f3.close()


def test_cache_persistence(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    f.import_bulk([3, 3, 4], [1, 2, 3])
    f.flush_cache()
    f2 = reopen(f)
    assert f2.cache.get(3) == 2
    assert f2.cache.get(4) == 1
    f2.close()


def test_lru_cache_type(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0, cache_type="lru")
    f.open()
    f.set_bit(1, 1)
    assert isinstance(f.cache, cm.LRUCache)
    assert f.top(TopOptions(n=1))[0].id == 1
    f.close()


def test_flock_excludes_second_opener(tmp_path, frag):
    f2 = Fragment(frag.path, "i", "f", "standard", 0)
    with pytest.raises(FragmentError, match="locked"):
        f2.open()


def test_for_each_bit_streams_rows(frag):
    """Iteration peak memory is one unpacked row, not the whole plane
    (VERDICT r1 item 10; reference streams via container iterators,
    roaring/roaring.go:742-840)."""
    import tracemalloc

    for r in range(16):
        frag.set_bit(r, r * 3)
        frag.set_bit(r, SW - 1 - r)
    want = sorted(
        [(r, r * 3) for r in range(16)] + [(r, SW - 1 - r) for r in range(16)]
    )
    tracemalloc.start()
    got = sorted(frag.for_each_bit())
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert got == want
    # One unpacked row is SLICE_WIDTH bytes (~1 MiB); the old
    # implementation unpacked all 16 rows at once (~17 MiB).
    assert peak < 3 * SW, f"peak {peak} suggests whole-plane unpack"


def test_for_each_bit(frag):
    frag.set_bit(2, 5)
    frag.set_bit(0, 1)
    assert sorted(frag.for_each_bit()) == [(0, 1), (2, 5)]


def test_blocks_checksum_canonical_across_padding(tmp_path):
    # Same logical bits, different plane-growth history -> same checksums
    a = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
    a.open()
    a.set_bit(0, 1)
    a.set_bit(103, 5)   # grows plane to 104+ rows
    a.clear_bit(103, 5)  # logical content back to just row 0
    b = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0)
    b.open()
    b.set_bit(0, 1)
    assert a.blocks() == b.blocks()
    assert a.checksum() == b.checksum()
    a.close()
    b.close()


def test_read_from_rejects_negative_cache_id(tmp_path, frag):
    frag.set_bit(0, 1)
    import io as _io, json as _json, tarfile as _tar
    buf = _io.BytesIO()
    frag.write_to(buf)
    # rebuild the tar with a poisoned cache member
    buf.seek(0)
    tr = _tar.open(fileobj=buf, mode="r|")
    members = {m.name: tr.extractfile(m).read() for m in tr}
    tr.close()
    # Drop the embedded checksum entry: a self-produced tar would
    # reject the poisoned cache outright (ArchiveChecksumError); this
    # test targets the cache-id validation behind that gate, i.e. a
    # legacy/foreign archive with no checksums.
    members.pop("checksum", None)
    members["cache"] = _json.dumps([-1, 0]).encode()
    out = _io.BytesIO()
    tw = _tar.open(fileobj=out, mode="w|")
    for name, payload in members.items():
        info = _tar.TarInfo(name)
        info.size = len(payload)
        tw.addfile(info, _io.BytesIO(payload))
    tw.close()
    out.seek(0)
    f2 = Fragment(str(tmp_path / "c"), "i", "f", "standard", 0)
    f2.open()
    f2.read_from(out)
    assert all(p.id >= 0 for p in f2.top(TopOptions()))
    f2.close()


def test_huge_row_id_rejected_before_mutation(frag):
    """rowID=-1 wraps to 2^64-1 at the executor boundary; the fragment
    must reject it with FragmentError before touching plane or op-log."""
    import pytest

    from pilosa_tpu.core.fragment import MAX_ROW_ID, FragmentError

    with pytest.raises(FragmentError):
        frag.set_bit((1 << 64) - 1, 1)
    with pytest.raises(FragmentError):
        frag.set_bit(MAX_ROW_ID, 1)
    # clearing a never-set row is a no-op, regardless of id
    assert frag.clear_bit((1 << 64) - 1, 1) is False
    assert frag.count() == 0


class TestIncrementalDeviceMirror:
    """Point writes after a device read apply as a batched scatter, not
    a full plane re-upload; bulk changes force re-upload."""

    def test_point_writes_visible_after_device_read(self, frag, monkeypatch):
        import jax
        import numpy as np

        from pilosa_tpu.ops import bitplane as bp

        frag.set_bit(1, 10)
        frag.device_plane()  # initial upload
        # From here on, point writes must apply as a device scatter —
        # any further full upload is a regression.
        uploads = []
        real_put = jax.device_put
        monkeypatch.setattr(
            jax, "device_put", lambda *a, **k: uploads.append(1) or real_put(*a, **k)
        )
        frag.set_bit(1, 20)
        frag.set_bit(2, 30)
        frag.clear_bit(1, 10)
        row1 = np.asarray(frag.device_row(1))
        assert bp.np_row_to_columns(row1).tolist() == [20]
        row2 = np.asarray(frag.device_row(2))
        assert bp.np_row_to_columns(row2).tolist() == [30]
        assert uploads == [], "point writes triggered a full plane re-upload"
        assert frag._device_pending == []

    def test_set_then_clear_same_bit_last_wins(self, frag):
        frag.set_bit(0, 5)
        frag.device_plane()
        frag.clear_bit(0, 5)
        frag.set_bit(0, 5)
        frag.clear_bit(0, 5)
        assert not frag.contains(0, 5)
        import numpy as np

        from pilosa_tpu.ops import bitplane as bp

        assert bp.np_row_to_columns(np.asarray(frag.device_row(0))).tolist() == []

    def test_small_bulk_import_scatters_mirror(self, frag):
        # A small import rides the delta-scatter path: the mirror stays
        # resident with the import's bits queued as pending deltas.
        frag.set_bit(0, 1)
        frag.device_plane()
        frag.import_bulk([0, 0], [2, 3])
        assert frag._device is not None
        assert frag._device_pending
        import numpy as np

        from pilosa_tpu.ops import bitplane as bp

        assert bp.np_row_to_columns(np.asarray(frag.device_row(0))).tolist() == [1, 2, 3]

    def test_large_bulk_import_invalidates_mirror(self, frag, monkeypatch):
        from pilosa_tpu.ingest import scatter as ingest_scatter

        monkeypatch.setattr(ingest_scatter, "IMPORT_SCATTER_MAX", 1)
        frag.set_bit(0, 1)
        frag.device_plane()
        frag.import_bulk([0, 0], [2, 3])
        assert frag._device is None  # full re-upload scheduled
        import numpy as np

        from pilosa_tpu.ops import bitplane as bp

        assert bp.np_row_to_columns(np.asarray(frag.device_row(0))).tolist() == [1, 2, 3]

    def test_overflow_degrades_to_reupload(self, frag):
        frag.set_bit(0, 0)
        frag.device_plane()
        cap = frag._MAX_DEVICE_PENDING
        cols = list(range(1, cap + 2))
        for c in cols:
            frag.set_bit(0, c)
        # the overflow branch must have invalidated the mirror
        assert frag._device is None
        import numpy as np

        from pilosa_tpu.ops import bitplane as bp

        got = bp.np_row_to_columns(np.asarray(frag.device_row(0)))
        assert got.tolist() == [0] + cols


def test_import_then_point_write_keeps_counts(frag):
    """Regression: import_bulk must refresh the incremental count map so
    later point writes don't poison the TopN cache with tiny counts."""
    frag.import_bulk([7] * 100, list(range(100)))
    frag.set_bit(7, 200)
    assert frag.cache.get(7) == 101
    frag.clear_bit(7, 200)
    assert frag.cache.get(7) == 100
    top = frag.top(TopOptions(n=1))
    assert [(p.id, p.count) for p in top] == [(7, 100)]


# ---------------------------------------------------------------------------
# sparse-tall fragments (two-tier storage; VERDICT r2 item 4)
# ---------------------------------------------------------------------------


def small_budget(tmp_path, budget=4, name="sp", max_op_n=10**9):
    f = Fragment(
        str(tmp_path / name), "i", "f", "standard", 0,
        dense_row_budget=budget, max_op_n=max_op_n,
    )
    f.open()
    return f


def test_sparse_tier_point_ops_parity(tmp_path):
    """With a tiny dense budget, rows spill to the sparse tier and every
    point op (set/clear/contains/row/count) behaves identically to a
    dense oracle fragment."""
    a = small_budget(tmp_path, budget=4, name="a")
    b = small_budget(tmp_path, budget=1 << 16, name="b")  # all-dense oracle
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 40, size=300)
    cols = rng.integers(0, SW, size=300)
    try:
        for r, c in zip(rows, cols):
            assert a.set_bit(int(r), int(c)) == b.set_bit(int(r), int(c))
        assert len(a._sparse) > 0 and len(a._slot_of) == 4
        assert a.count() == b.count()
        assert a.row_counts() == b.row_counts()
        for r in range(40):
            assert a.row(r).bits() == b.row(r).bits(), r
        for r, c in zip(rows[:50], cols[:50]):
            assert a.contains(int(r), int(c))
            assert a.clear_bit(int(r), int(c)) == b.clear_bit(int(r), int(c))
            assert not a.contains(int(r), int(c))
        assert a.count() == b.count()
    finally:
        a.close()
        b.close()


def test_sparse_tier_persistence_roundtrip(tmp_path):
    """Sparse-tier rows survive snapshot + reopen (tiered roaring
    encode/decode) and the op-log replay path."""
    f = small_budget(tmp_path, budget=2, max_op_n=10**9)
    bits = [(0, 5), (1, 9), (2, 11), (3, 70000), (1000, 123), (999999, SW - 1)]
    for r, c in bits:
        f.set_bit(r, c)
    f.snapshot()
    f.set_bit(12345, 42)  # post-snapshot op-log entry
    f2 = reopen(f)
    try:
        got = sorted(f2.for_each_bit())
        assert got == sorted((r, c) for r, c in bits + [(12345, 42)])
        assert f2.count() == len(bits) + 1
    finally:
        f2.close()


def test_sparse_tall_inverse_scale(tmp_path):
    """An inverse-style fragment with 200k distinct rows in ONE slice
    imports, queries, checksums, and reopens — and memory scales with
    set bits, not rows x 128 KiB (the dense plane stays at the budget)."""
    f = small_budget(tmp_path, budget=64, name="tall")
    n = 200_000
    rows = np.arange(n, dtype=np.int64)          # row axis = column space
    cols = (rows * 31) % SW                      # one bit per row
    try:
        f.import_bulk(rows, cols)
        assert len(f._sparse) >= n - 64
        assert f._plane.shape[0] <= 64           # dense tier at budget
        assert f.count() == n
        # point query on a sparse row
        assert f.contains(123_456, int(cols[123_456]))
        assert f.row(123_456).bits() == [int(cols[123_456])]
        # device leaf for a sparse row pages on demand
        dr = f.device_row(123_456)
        assert dr is not None and int(np.asarray(dr).sum()) > 0
        # anti-entropy machinery covers sparse rows
        blocks = f.blocks()
        assert len(blocks) == n // 100
        ps = f.block_data(1234)
        assert len(ps.row_ids) == 100
        f2 = reopen(f)
        try:
            assert f2.count() == n
            assert f2.contains(199_999, int(cols[199_999]))
        finally:
            f2.close()
    finally:
        f.close()


def test_sparse_checksums_match_dense_replica(tmp_path):
    """Block checksums depend only on logical content: a budget-starved
    (mostly sparse) replica and an all-dense replica of the same bits
    produce identical checksums — anti-entropy never sees phantom
    diffs between tiers."""
    rng = np.random.default_rng(3)
    n = 1200
    rows = np.repeat(np.arange(n, dtype=np.int64), 2)
    cols = rng.integers(0, SW, size=2 * n)
    a = small_budget(tmp_path, budget=16, name="sparse-rep")
    b = small_budget(tmp_path, budget=1 << 16, name="dense-rep")
    try:
        a.import_bulk(rows, cols)
        b.import_bulk(rows, cols)
        assert len(a._sparse) > 0 and len(b._sparse) == 0
        assert a.blocks() == b.blocks()
        assert a.checksum() == b.checksum()
    finally:
        a.close()
        b.close()


def test_sparse_promotion_to_dense(tmp_path):
    """A sparse row crossing PROMOTE_BITS moves into the dense tier when
    budget remains."""
    import pilosa_tpu.core.fragment as fr

    f = small_budget(tmp_path, budget=8)
    try:
        for r in range(6):
            f.set_bit(r, r)  # fill some dense slots
        # row 100 starts sparse only if budget exhausted — force sparse
        f.dense_row_budget = 6
        f.set_bit(100, 0)
        assert 100 in f._sparse
        f.dense_row_budget = 8
        offs = np.arange(fr.PROMOTE_BITS + 2, dtype=np.int64)
        f.import_bulk(np.full(len(offs), 100, dtype=np.int64), offs)
        assert 100 in f._slot_of and 100 not in f._sparse
        assert f._count_of[100] == fr.PROMOTE_BITS + 2
        assert f.row(100).count() == fr.PROMOTE_BITS + 2
    finally:
        f.close()


def test_sparse_merge_block_consensus(tmp_path):
    """merge_block consensus works across tiers: a sparse-tier row takes
    part in majority merge."""
    f = small_budget(tmp_path, budget=1)
    try:
        f.set_bit(0, 1)      # dense
        f.set_bit(5, 2)      # sparse (budget 1)
        assert 5 in f._sparse
        remote1 = PairSet(row_ids=[5, 7], column_ids=[2, 3])
        remote2 = PairSet(row_ids=[5, 7], column_ids=[2, 3])
        sets, clears = f.merge_block(0, [remote1, remote2])
        # consensus: (5,2) 3/3 kept; (7,3) 2/3 set locally; (0,1) 1/3 cleared
        assert f.contains(5, 2) and f.contains(7, 3)
        assert not f.contains(0, 1)
    finally:
        f.close()


def test_sparse_topn_candidates(tmp_path):
    """TopN scores sparse-tier candidates (host O(bits) path) together
    with dense ones."""
    f = small_budget(tmp_path, budget=1)
    try:
        for c in range(50):
            f.set_bit(0, c)          # dense row, 50 bits
        for c in range(30):
            f.set_bit(1, 2 * c)      # sparse row, 30 bits
        for c in range(10):
            f.set_bit(2, 4 * c)      # sparse row, 10 bits
        assert 1 in f._sparse and 2 in f._sparse
        src = f.row(0)
        got = f.top(TopOptions(n=3, src=src))
        assert [(p.id, p.count) for p in got] == [(0, 50), (1, 25), (2, 10)]
    finally:
        f.close()


def test_oplog_group_commit(tmp_path):
    """Point writes buffer op records (no per-bit file growth) and every
    flush boundary — explicit flush_ops, threshold, close — persists
    them; a reopen replays the flushed ops."""
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0, max_op_n=10_000)
    f.open()
    base = os.path.getsize(f.path)
    f.set_bit(1, 10)
    f.set_bit(1, 20)
    assert os.path.getsize(f.path) == base, "ops must buffer, not write per bit"
    assert len(f._op_buf) > 0
    f.flush_ops()
    assert os.path.getsize(f.path) > base
    assert len(f._op_buf) == 0
    # close() is a flush boundary for whatever is still buffered
    f.set_bit(2, 30)
    f2 = reopen(f)
    assert f2.row(1).bits() == [10, 20]
    assert f2.row(2).bits() == [30]
    # threshold flush: exceed _OP_FLUSH_BYTES without any boundary
    n_ops = Fragment._OP_FLUSH_BYTES // 13 + 2
    before = os.path.getsize(f2.path)
    for i in range(n_ops):
        f2.set_bit(3, i)
    assert os.path.getsize(f2.path) > before, "threshold flush did not fire"
    f2.close()


def test_csv_chunks_matches_for_each_bit(frag):
    frag.set_bit(0, 1)
    frag.set_bit(2, 5)
    frag.set_bit(2, SW - 1)
    blob = b"".join(frag.csv_chunks())
    want = "".join(f"{r},{c}\n" for r, c in sorted(frag.for_each_bit()))
    assert blob.decode() == want


def test_csv_chunks_vectorized_batching(tmp_path, monkeypatch):
    """Export must be row-block vectorized: the formatter is handed whole
    record arrays (a handful of calls for millions of bits), never driven
    per bit.  Structural check, deterministic on any CI speed; the
    measured throughput (~14M pairs/s vs ~1M for the old per-bit loop)
    is recorded in BASELINE.md."""
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0, max_op_n=10**9)
    f.open()
    rng = np.random.default_rng(3)
    for r in range(8):
        cols = np.unique(rng.integers(0, SW, SW // 8))
        f.import_bulk(np.full(len(cols), r), cols)
    total = f.count()
    assert total > 500_000

    calls = []
    real = Fragment._format_pairs

    def spy(rws, cls):
        calls.append(len(rws))
        return real(rws, cls)

    monkeypatch.setattr(Fragment, "_format_pairs", staticmethod(spy))
    pairs = sum(chunk.count(b"\n") for chunk in f.csv_chunks(chunk_pairs=1 << 18))
    f.close()
    assert pairs == total
    assert sum(calls) == total
    # ceil(total / chunk) + 1 slack: each call must carry ~chunk records
    assert len(calls) <= total // (1 << 18) + 2, calls


def test_open_corrupt_file_raises_corrupt_error(tmp_path):
    """A corrupt data file must surface roaring.CorruptError from open,
    not a BufferError from closing the mmap while decode-exception
    traceback frames still hold buffer views of it."""
    from pilosa_tpu.ops import roaring as roaring_mod

    path = tmp_path / "0"
    path.write_bytes(b"\x00" * 64)  # wrong cookie
    f = Fragment(str(path), "i", "f", "standard", 0)
    with pytest.raises(roaring_mod.CorruptError):
        f.open()
    # and with the native decoder disabled (pure-Python buffer views)
    import os as _os
    from unittest import mock

    f2 = Fragment(str(path), "i", "f", "standard", 0)
    with mock.patch.dict(_os.environ, {"PILOSA_TPU_DISABLE_NATIVE": "1"}):
        with pytest.raises(roaring_mod.CorruptError):
            f2.open()


# ---------------------------------------------------------------------------
# torn op-log tail recovery (WAL repair on open)
# reference: roaring/roaring.go:622-646 (op replay), fragment.go:154-242
# ---------------------------------------------------------------------------


def _frag_with_oplog(tmp_path, n_ops=50):
    """A closed fragment file whose op-log holds n_ops SetBit records."""
    path = str(tmp_path / "wal")
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    for i in range(n_ops):
        f.set_bit(1, i)
    f.close()
    return path


def _reopen_and_bits(path):
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    bits = f.row(1).bits()
    f.close()
    return bits


@pytest.mark.parametrize("native", [True, False])
def test_torn_tail_partial_record_truncated_on_open(tmp_path, native, monkeypatch):
    from pilosa_tpu.ops import roaring as rg

    if not native:
        monkeypatch.setenv("PILOSA_TPU_DISABLE_NATIVE", "1")
    path = _frag_with_oplog(tmp_path)
    healthy = os.path.getsize(path)
    with open(path, "ab") as fh:
        fh.write(b"\x01\x02\x03\x04\x05")  # 5-byte torn record
    assert _reopen_and_bits(path) == list(range(50))
    assert os.path.getsize(path) == healthy
    assert rg.check(open(path, "rb").read()) == []


@pytest.mark.parametrize("native", [True, False])
def test_torn_tail_multi_record_garbage_truncated(tmp_path, native, monkeypatch):
    """Group commit can tear MULTIPLE records: a crash mid-64KiB-flush
    leaves full-size garbage records plus a partial one.  All of it must
    go; the committed prefix must survive."""
    from pilosa_tpu.ops import roaring as rg

    if not native:
        monkeypatch.setenv("PILOSA_TPU_DISABLE_NATIVE", "1")
    path = _frag_with_oplog(tmp_path)
    healthy = os.path.getsize(path)
    with open(path, "ab") as fh:
        fh.write(b"\x00" * (13 * 3 + 7))  # 3 bad checksums + torn tail
    assert _reopen_and_bits(path) == list(range(50))
    assert os.path.getsize(path) == healthy
    assert rg.check(open(path, "rb").read()) == []


def test_torn_tail_mid_record_truncation_keeps_prefix(tmp_path):
    """File cut mid-record (crash during append): ops before the cut
    survive, the partial record is dropped."""
    from pilosa_tpu.ops import roaring as rg

    path = _frag_with_oplog(tmp_path)
    ops_at = rg.ops_region_offset(open(path, "rb").read())
    keep = 20
    with open(path, "r+b") as fh:
        fh.truncate(ops_at + keep * 13 + 6)
    assert _reopen_and_bits(path) == list(range(keep))
    assert os.path.getsize(path) == ops_at + keep * 13


def test_mid_log_corruption_with_later_valid_ops_refuses(tmp_path):
    """Damage to COMMITTED data (valid records beyond the bad one) is
    not a torn tail — open must refuse, not silently drop ops."""
    from pilosa_tpu.ops import roaring as rg

    path = _frag_with_oplog(tmp_path)
    data = bytearray(open(path, "rb").read())
    ops_at = rg.ops_region_offset(bytes(data))
    data[ops_at + 5 * 13 + 3] ^= 0xFF  # flip a byte inside op #5's value
    open(path, "wb").write(bytes(data))
    f = Fragment(path, "i", "f", "standard", 0)
    with pytest.raises(rg.CorruptError):
        f.open()


def test_torn_tail_on_empty_container_section(tmp_path):
    """A fresh fragment (header only) with a torn first op recovers to
    the bare header."""
    path = str(tmp_path / "wal")
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    f.close()
    with open(path, "ab") as fh:
        fh.write(b"\x00\xaa\xbb")
    assert _reopen_and_bits(path) == []


def test_scan_torn_tail_healthy_returns_none(tmp_path):
    from pilosa_tpu.ops import roaring as rg

    path = _frag_with_oplog(tmp_path)
    assert rg.scan_torn_tail(open(path, "rb").read()) is None


def test_container_damage_plus_tail_garbage_leaves_file_untouched(tmp_path):
    """Corruption OUTSIDE the op tail (here: an unsorted array container)
    must refuse to open even when tail garbage makes the op region look
    torn — and the file bytes must be left intact for forensics."""
    from pilosa_tpu.ops import roaring as rg

    path = str(tmp_path / "wal")
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    f.import_bulk(np.array([1, 1, 1]), np.array([5, 9, 30]))
    f.close()
    data = bytearray(open(path, "rb").read())
    # swap the first two sorted u32 array values -> unsorted container
    off = rg.ops_region_offset(bytes(data)) - 12
    data[off : off + 8] = data[off + 4 : off + 8] + data[off : off + 4]
    data += b"\x00" * 20  # tail garbage that alone would be "torn"
    open(path, "wb").write(bytes(data))
    before = open(path, "rb").read()
    f2 = Fragment(path, "i", "f", "standard", 0)
    with pytest.raises(rg.CorruptError):
        f2.open()
    assert open(path, "rb").read() == before


def test_oversized_invalid_tail_refuses(tmp_path):
    """An invalid tail bigger than one group-commit flush buffer cannot
    be crash residue (writes flush at 64 KiB) — it is at-rest damage to
    committed data and must refuse to load, file untouched."""
    from pilosa_tpu.ops import roaring as rg

    path = _frag_with_oplog(tmp_path, n_ops=10)
    with open(path, "ab") as fh:
        fh.write(b"\x00" * ((64 << 10) + 1024))
    before = open(path, "rb").read()
    f = Fragment(path, "i", "f", "standard", 0)
    with pytest.raises(rg.CorruptError):
        f.open()
    assert open(path, "rb").read() == before


def test_torn_tail_on_log_larger_than_scan_window(tmp_path):
    """The scanner fast-forwards to the last flush-buffer window on big
    logs; a torn tail on a >64 KiB op-log still repairs correctly."""
    from pilosa_tpu.ops import roaring as rg

    n_ops = 8000  # 104 KB of op records > MAX_TORN_TAIL
    path = _frag_with_oplog(tmp_path, n_ops=n_ops)
    healthy = os.path.getsize(path)
    with open(path, "ab") as fh:
        fh.write(b"\x00" * (13 * 2 + 5))
    assert _reopen_and_bits(path) == list(range(n_ops))
    assert os.path.getsize(path) == healthy
    assert rg.check(open(path, "rb").read()) == []


# ---------------------------------------------------------------------------
# streaming loader (_load_direct) edge cases
# ---------------------------------------------------------------------------


def test_load_direct_multi_chunk_array_gather(tmp_path, monkeypatch):
    """Array-container values gather in bounded chunks; shrinking the
    chunk size forces many sweeps (with cross-chunk container
    boundaries) and the result must be identical."""
    monkeypatch.setattr(Fragment, "_LOAD_CHUNK_VALUES", 1 << 10)
    path = str(tmp_path / "chunky")
    f = Fragment(path, "i", "f", "standard", 0, dense_row_budget=8)
    f.open()
    rng = np.random.default_rng(5)
    rows_l, cols_l = [], []
    for r in range(40):  # ~120k values >> 1024-value chunks
        cols = np.unique(rng.integers(0, 1 << 20, 3200, dtype=np.int64))
        rows_l.append(np.full(len(cols), r, dtype=np.int64))
        cols_l.append(cols)
    f.import_bulk(np.concatenate(rows_l), np.concatenate(cols_l))
    expect = {r: f.row(r).bits() for r in (0, 7, 8, 23, 39)}
    expect_counts = f.row_counts()
    f.close()

    f2 = Fragment(path, "i", "f", "standard", 0, dense_row_budget=8)
    f2.open()
    assert len(f2._sparse) == 32  # 8 dense + 32 sparse
    for r, bits in expect.items():
        assert f2.row(r).bits() == bits, f"row {r}"
    assert f2.row_counts() == expect_counts
    f2.close()


def test_load_rejects_unsorted_container_keys(tmp_path):
    """Out-of-order container keys would silently break the sparse
    tier's binary searches — open must refuse (fail-fast standard)."""
    from pilosa_tpu.ops import roaring as rg

    # containers at keys [1, 0]: swap the two key-table entries
    data = rg.encode_tiered(
        {}, {0: np.array([7], np.uint32), 1: np.array([5], np.uint32)}
    )
    raw = bytearray(data)
    k0 = raw[8 : 8 + 12]
    raw[8 : 8 + 12] = raw[20 : 20 + 12]
    raw[20 : 20 + 12] = k0
    path = str(tmp_path / "unsorted")
    open(path, "wb").write(bytes(raw))
    f = Fragment(path, "i", "f", "standard", 0)
    with pytest.raises(rg.CorruptError, match="not sorted"):
        f.open()


def test_load_counts_come_from_payload_not_header_n(tmp_path):
    """A corrupt bitmap-container n must not poison Count/TopN: counts
    recompute from the payload on open (header n only drives tier
    ranking)."""
    from pilosa_tpu.ops import roaring as rg

    path = str(tmp_path / "badn")
    f = Fragment(path, "i", "f", "standard", 0)
    f.open()
    f.import_bulk(np.zeros(5000, np.int64), np.arange(5000, dtype=np.int64))
    f.close()
    raw = bytearray(open(path, "rb").read())
    # single bitmap container (n=5000 > 4096): inflate header n
    (n1,) = np.frombuffer(bytes(raw[16:20]), "<u4")
    assert n1 + 1 == 5000
    raw[16:20] = np.uint32(59999).tobytes()  # claims n=60000
    open(path, "wb").write(bytes(raw))
    f2 = Fragment(path, "i", "f", "standard", 0)
    f2.open()
    assert f2.count() == 5000
    assert f2.row_counts()[0] == 5000
    f2.close()


# ---------------------------------------------------------------------------
# protobuf .cache format parity (reference: internal/private.proto Cache,
# fragment.go:1076-1110)
# ---------------------------------------------------------------------------


def test_cache_file_is_reference_protobuf(tmp_path):
    """flush_cache writes the reference's protobuf Cache message (same
    field numbers), so a real Pilosa can parse our .cache files."""
    from pilosa_tpu.net import wire_pb2 as wire

    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    for r in (3, 1, 7):
        for c in range(r + 1):
            f.set_bit(r, c)
    f.flush_cache()
    payload = open(f.cache_path, "rb").read()
    msg = wire.Cache()
    msg.ParseFromString(payload)
    assert sorted(msg.IDs) == [1, 3, 7]
    f.close()


def test_cache_json_backcompat_still_loads(tmp_path):
    """r01-r04 wrote the cache as a JSON list; those files must keep
    loading after an upgrade."""
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    for c in range(5):
        f.set_bit(9, c)
    f.close()
    open(f.cache_path, "w").write("[9]")  # overwrite with the old format
    f2 = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f2.open()
    assert f2.cache.get(9) == 5
    f2.close()


def test_reference_made_tar_cache_entry_restores(tmp_path):
    """A backup tar whose "cache" entry is the reference's protobuf
    Cache message restores the cache here (cross-implementation
    restore)."""
    import io as _io
    import tarfile as _tarfile

    from pilosa_tpu.net import wire_pb2 as wire
    from pilosa_tpu.ops import roaring as rg
    from tests.conftest import positions_to_words

    # build the tar the way the reference would: roaring data + pb cache
    words = {0: positions_to_words([1, 2, 3]), 16: positions_to_words([4])}
    data = rg.encode(words)  # rows 0 and 1 (key 16 = row 1)
    cache_pb = wire.Cache(IDs=[0, 1]).SerializeToString()
    buf = _io.BytesIO()
    tw = _tarfile.open(fileobj=buf, mode="w|")
    for name, payload in (("data", data), ("cache", cache_pb)):
        ti = _tarfile.TarInfo(name)
        ti.size = len(payload)
        tw.addfile(ti, _io.BytesIO(payload))
    tw.close()
    buf.seek(0)

    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    f.read_from(buf)
    assert f.cache.get(0) == 3 and f.cache.get(1) == 1
    assert f.row(0).bits() == [1, 2, 3]
    f.close()


def test_tar_roundtrip_cache_is_protobuf(tmp_path):
    """Our own backup tars carry the protobuf cache entry and restore
    it."""
    import io as _io
    import tarfile as _tarfile

    from pilosa_tpu.net import wire_pb2 as wire

    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
    f.open()
    for c in range(4):
        f.set_bit(2, c)
    buf = _io.BytesIO()
    f.write_to(buf)
    f.close()
    buf.seek(0)
    tr = _tarfile.open(fileobj=_io.BytesIO(buf.getvalue()), mode="r|")
    names = {}
    for m in tr:
        names[m.name] = tr.extractfile(m).read()
    msg = wire.Cache()
    msg.ParseFromString(names["cache"])
    assert list(msg.IDs) == [2]

    f2 = Fragment(str(tmp_path / "1"), "i", "f", "standard", 0)
    f2.open()
    buf.seek(0)
    f2.read_from(buf)
    assert f2.cache.get(2) == 4
    f2.close()
