"""Executor tests — single-node and mocked-remote map/reduce
(parity tier for executor_test.go)."""

from datetime import datetime

import pytest

from pilosa_tpu.cluster.topology import new_cluster
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.view import VIEW_INVERSE, VIEW_STANDARD
from pilosa_tpu.exec import (
    ExecOptions,
    Executor,
    ExecutorError,
    SlicesUnavailableError,
    TooManyWritesError,
)
from pilosa_tpu.ops.bitplane import SLICE_WIDTH
from pilosa_tpu.pql.parser import parse_string


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def ex(holder):
    """Single-node executor pinned to node 0 (reference:
    executor_test.go:758-770)."""
    c = new_cluster(1)
    return Executor(holder, host=c.nodes[0].host, cluster=c)


def must_set_bits(holder, index, frame, bits, view=VIEW_STANDARD):
    idx = holder.create_index_if_not_exists(index)
    f = idx.create_frame_if_not_exists(frame)
    for row, col in bits:
        f.set_bit(view, row, col)
    return f


def q(ex, index, pql, slices=None, opt=None):
    return ex.execute(index, parse_string(pql), slices, opt)


# --- bitmap reads (reference: executor_test.go:31-205) ---------------------


def test_execute_bitmap(ex, holder):
    f = must_set_bits(
        holder, "i", "f", [(10, 3), (10, SLICE_WIDTH + 1)]
    )
    f.row_attr_store.set_attrs(10, {"foo": "bar", "baz": 123})
    (bm,) = q(ex, "i", "Bitmap(rowID=10, frame=f)")
    assert bm.bits() == [3, SLICE_WIDTH + 1]
    assert bm.attrs == {"foo": "bar", "baz": 123}


def test_execute_bitmap_default_frame(ex, holder):
    must_set_bits(holder, "i", "general", [(10, 3)])
    (bm,) = q(ex, "i", "Bitmap(rowID=10)")
    assert bm.bits() == [3]


def test_execute_intersect_difference_union_count(ex, holder):
    must_set_bits(
        holder,
        "i",
        "f",
        [(10, 0), (10, 1), (10, SLICE_WIDTH + 2), (11, 1), (11, SLICE_WIDTH + 2)],
    )
    (bm,) = q(ex, "i", "Intersect(Bitmap(rowID=10, frame=f), Bitmap(rowID=11, frame=f))")
    assert bm.bits() == [1, SLICE_WIDTH + 2]
    (bm,) = q(ex, "i", "Union(Bitmap(rowID=10, frame=f), Bitmap(rowID=11, frame=f))")
    assert bm.bits() == [0, 1, SLICE_WIDTH + 2]
    (bm,) = q(ex, "i", "Difference(Bitmap(rowID=10, frame=f), Bitmap(rowID=11, frame=f))")
    assert bm.bits() == [0]
    (n,) = q(ex, "i", "Count(Union(Bitmap(rowID=10, frame=f), Bitmap(rowID=11, frame=f)))")
    assert n == 3


def test_execute_nested_tree(ex, holder):
    must_set_bits(holder, "i", "f", [(1, 1), (1, 2), (2, 2), (2, 3), (3, 3)])
    (n,) = q(
        ex,
        "i",
        "Count(Union(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)),"
        " Bitmap(rowID=3, frame=f)))",
    )
    assert n == 2  # {2} | {3}


def test_execute_empty_intersect_errors(ex, holder):
    must_set_bits(holder, "i", "f", [(1, 1)])
    with pytest.raises(Exception, match="empty Intersect"):
        q(ex, "i", "Count(Intersect())")


def test_execute_count_requires_child(ex, holder):
    must_set_bits(holder, "i", "f", [(1, 1)])
    with pytest.raises(ExecutorError, match="requires an input"):
        q(ex, "i", "Count()")


def test_bitmap_missing_row_and_col(ex, holder):
    must_set_bits(holder, "i", "f", [(1, 1)])
    with pytest.raises(ExecutorError, match="must specify"):
        q(ex, "i", "Bitmap(frame=f)")


def test_inverse_bitmap(ex, holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f", inverse_enabled=True)
    # Writing through the executor populates both orientations.
    q(ex, "i", "SetBit(frame=f, rowID=10, columnID=3)")
    q(ex, "i", "SetBit(frame=f, rowID=11, columnID=3)")
    (bm,) = q(ex, "i", "Bitmap(columnID=3, frame=f)")
    assert bm.bits() == [10, 11]


def test_inverse_requires_enabled(ex, holder):
    must_set_bits(holder, "i", "f", [(1, 1)])
    with pytest.raises(ExecutorError, match="inverse storage enabled"):
        q(ex, "i", "Bitmap(columnID=1, frame=f)")


# --- writes ----------------------------------------------------------------


def test_set_and_clear_bit(ex, holder):
    holder.create_index("i").create_frame("f")
    (changed,) = q(ex, "i", "SetBit(frame=f, rowID=1, columnID=9)")
    assert changed is True
    (changed,) = q(ex, "i", "SetBit(frame=f, rowID=1, columnID=9)")
    assert changed is False
    (n,) = q(ex, "i", "Count(Bitmap(rowID=1, frame=f))")
    assert n == 1
    (changed,) = q(ex, "i", "ClearBit(frame=f, rowID=1, columnID=9)")
    assert changed is True
    (n,) = q(ex, "i", "Count(Bitmap(rowID=1, frame=f))")
    assert n == 0


def test_setbit_with_timestamp_and_range(ex, holder):
    idx = holder.create_index("i")
    idx.create_frame("f", time_quantum="YMDH")
    q(ex, "i", 'SetBit(frame=f, rowID=1, columnID=2, timestamp="2010-01-01T00:00")')
    q(ex, "i", 'SetBit(frame=f, rowID=1, columnID=3, timestamp="2010-03-01T00:00")')
    q(ex, "i", 'SetBit(frame=f, rowID=1, columnID=4, timestamp="2011-01-01T00:00")')
    (bm,) = q(
        ex, "i",
        'Range(rowID=1, frame=f, start="2010-01-01T00:00", end="2010-12-31T23:59")',
    )
    assert bm.bits() == [2, 3]


def test_set_row_attrs(ex, holder):
    holder.create_index("i").create_frame("f")
    q(ex, "i", 'SetRowAttrs(frame=f, rowID=7, alpha="beta", n=123)')
    assert holder.frame("i", "f").row_attr_store.attrs(7) == {"alpha": "beta", "n": 123}


def test_bulk_set_row_attrs(ex, holder):
    holder.create_index("i").create_frame("f")
    res = q(ex, "i", 'SetRowAttrs(frame=f, rowID=1, a=1) SetRowAttrs(frame=f, rowID=2, b=2)')
    assert res == [None, None]
    store = holder.frame("i", "f").row_attr_store
    assert store.attrs(1) == {"a": 1}
    assert store.attrs(2) == {"b": 2}


def test_set_column_attrs(ex, holder):
    holder.create_index("i")
    q(ex, "i", 'SetColumnAttrs(id=99, x="y")')
    assert holder.index("i").column_attr_store.attrs(99) == {"x": "y"}


def test_max_writes_guard(holder):
    c = new_cluster(1)
    e = Executor(holder, host=c.nodes[0].host, cluster=c, max_writes_per_request=2)
    holder.create_index("i").create_frame("f")
    pql = " ".join(f"SetBit(frame=f, rowID=1, columnID={i})" for i in range(3))
    with pytest.raises(TooManyWritesError):
        q(e, "i", pql)


# --- TopN (reference: executor_test.go:207-376) ----------------------------


def test_topn(ex, holder):
    bits = [(0, i) for i in range(5)] + [(10, i) for i in range(3)] + [(12, 5)]
    bits += [(0, SLICE_WIDTH + i) for i in range(2)]
    must_set_bits(holder, "i", "f", bits)
    (pairs,) = q(ex, "i", "TopN(frame=f, n=2)")
    assert [(p.id, p.count) for p in pairs] == [(0, 7), (10, 3)]


def test_topn_with_src(ex, holder):
    must_set_bits(
        holder, "i", "f",
        [(0, 0), (0, 1), (0, 2), (10, 1), (10, 2), (12, 2)],
    )
    (pairs,) = q(ex, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=2)")
    assert [(p.id, p.count) for p in pairs] == [(0, 3), (10, 2)]


def test_topn_src_many_slices(ex, holder):
    """TopN with a src bitmap spanning MANY slices: the executor
    prepares every slice then resolves all dense score vectors in one
    bulk fetch — counts must equal the per-slice sum of |row ∩ src|
    exactly (two-phase refetch included)."""
    n_slices = 9
    bits = []
    # src row 0: columns 0..9 of every slice EXCEPT slice 4 (that
    # fragment never exists — prepare must skip it); rows 1..3 overlap
    # differently per slice.
    for s in range(n_slices):
        if s == 4:
            continue
        base = s * SLICE_WIDTH
        bits += [(0, base + c) for c in range(10)]
        bits += [(1, base + c) for c in range(0, 10, 2)]        # 5/slice
        bits += [(2, base + c) for c in range(0, 10, 3)]        # 4/slice
        if s % 2 == 0:
            bits += [(3, base + c) for c in range(10)]          # 10 on even slices
    must_set_bits(holder, "i", "f", bits)
    (pairs,) = q(ex, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=4)")
    got = {p.id: p.count for p in pairs}
    populated = n_slices - 1  # slice 4 has no fragment at all
    assert got[0] == 10 * populated
    assert got[3] == 10 * 4   # even slices 0,2,6,8
    assert got[1] == 5 * populated
    assert got[2] == 4 * populated


def test_topn_ids(ex, holder):
    must_set_bits(holder, "i", "f", [(0, 0), (0, 1), (10, 1), (12, 2)])
    (pairs,) = q(ex, "i", "TopN(frame=f, ids=[0, 12])")
    assert [(p.id, p.count) for p in pairs] == [(0, 2), (12, 1)]


def test_topn_fill(ex, holder):
    """reference: executor_test.go:328-349 TestExecutor_Execute_TopN_fill
    — the global winner needs exact counts summed across slices even
    when per-slice phase-1 lists disagree."""
    must_set_bits(
        holder, "i", "f",
        [(0, 0), (0, 1), (0, 2),
         (0, SLICE_WIDTH), (1, SLICE_WIDTH + 2), (1, SLICE_WIDTH)],
    )
    (pairs,) = q(ex, "i", "TopN(frame=f, n=1)")
    assert [(p.id, p.count) for p in pairs] == [(0, 4)]


def test_topn_fill_small(ex, holder):
    """reference: executor_test.go:352-382 TestExecutor_Execute_TopN_
    fill_small — a row that is never any single slice's per-slice
    winner by margin still wins globally once counts are summed."""
    bits = [(0, s * SLICE_WIDTH) for s in range(5)]
    bits += [(1, 0), (1, 1)]
    bits += [(2, SLICE_WIDTH), (2, SLICE_WIDTH + 1)]
    bits += [(3, 2 * SLICE_WIDTH), (3, 2 * SLICE_WIDTH + 1)]
    bits += [(4, 3 * SLICE_WIDTH), (4, 3 * SLICE_WIDTH + 1)]
    must_set_bits(holder, "i", "f", bits)
    (pairs,) = q(ex, "i", "TopN(frame=f, n=1)")
    assert [(p.id, p.count) for p in pairs] == [(0, 5)]


def test_read_calls_counted_with_index_tag(ex, holder):
    """Read calls fire a per-call-name counter tagged index:<name>
    (reference: executor.go:163-181, stats_test.go:75-131)."""
    must_set_bits(holder, "i", "f", [(0, 0), (0, 1)])
    calls = []

    class Spy:
        def count_with_custom_tags(self, name, value, tags):
            calls.append((name, value, tuple(tags)))

        def __getattr__(self, _):
            return lambda *a, **k: None

    holder.stats = Spy()
    q(ex, "i", "TopN(frame=f, n=1)")
    q(ex, "i", "Count(Bitmap(rowID=0, frame=f))")
    q(ex, "i", "Bitmap(rowID=0, frame=f)")
    assert ("TopN", 1, ("index:i",)) in calls
    assert ("Count", 1, ("index:i",)) in calls
    assert ("Bitmap", 1, ("index:i",)) in calls


def test_topn_fused_scorer_group_padding(ex, holder):
    """A 5-slice group pads to the 8-bucket in the fused scorer; the
    surplus (repeated) members' scores must never leak into results,
    and a repeat query returns identical pairs."""
    bits = []
    for s in range(5):
        base = s * SLICE_WIDTH
        for r in range(6):
            bits += [(r, base + k) for k in range(r + 2)]
    must_set_bits(holder, "i", "f", bits)
    pql = "TopN(Bitmap(rowID=0, frame=f), frame=f, n=4)"
    (want,) = q(ex, "i", pql)
    assert want
    # row r intersects row 0 on min(r+2, 2) = 2 columns per slice.
    got = {p.id: p.count for p in want}
    assert got[0] == 10  # |row0| = 2 bits x 5 slices
    assert all(v == 10 for v in got.values())
    (again,) = q(ex, "i", pql)
    assert [(p.id, p.count) for p in again] == [(p.id, p.count) for p in want]


def test_topn_src_mutated_falls_back_to_snapshot(ex, holder):
    """When no same-plane src slot is available (different src frame,
    sparse-tier src row, or a mirror refresh since the prepare
    snapshot), the scorer falls back to the one host-snapshot src
    transfer; forcing that path must produce exactly the same
    results."""
    bits = []
    for s in range(3):
        base = s * SLICE_WIDTH
        bits += [(0, base), (0, base + 1), (1, base), (2, base + 1)]
    must_set_bits(holder, "i", "f", bits)

    # Drop every same-plane src slot so the host-snapshot path runs.
    orig = ex._attach_dev_src

    def attach_force_host_src(index, c, frag, part):
        st, sub, srcw, _slot = orig(index, c, frag, part)
        return st, sub, srcw, None

    ex._attach_dev_src = attach_force_host_src
    try:
        (pairs,) = q(ex, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=3)")
    finally:
        ex._attach_dev_src = orig
    got = {p.id: p.count for p in pairs}
    # row0 ∩ row0 = 6 bits; row1 ∩ row0 = 3 (col 0 per slice);
    # row2 ∩ row0 = 3 (col 1 per slice)
    assert got == {0: 6, 1: 3, 2: 3}


def test_topn_duplicate_ids_not_double_counted(ex, holder):
    """A duplicated explicit id must not be scored twice (the cross-
    slice merge SUMS counts by id, so a duplicate would double the
    reported count)."""
    must_set_bits(holder, "i", "f", [(0, 0), (0, 1), (0, 2)])
    (pairs,) = q(ex, "i", "TopN(frame=f, ids=[0, 0])")
    assert [(p.id, p.count) for p in pairs] == [(0, 3)]


def test_topn_tanimoto_bounds(ex, holder):
    must_set_bits(holder, "i", "f", [(0, 0)])
    with pytest.raises(ExecutorError, match="Tanimoto"):
        q(ex, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=2, tanimotoThreshold=150)")


# --- remote fan-out with a mock client (reference:
# executor_test.go:520-745 TestExecutor_Execute_Remote_*) -------------------


class MockClient:
    """Function-mock internal client (reference: handler_test.go:964-974
    HandlerExecutor.ExecuteFn pattern)."""

    def __init__(self, fn):
        self.fn = fn
        self.calls = []

    def execute_query(self, index, query, slices, remote):
        self.calls.append((index, query, list(slices or []), remote))
        return self.fn(index, query, slices, remote)


def test_remote_count_merges(holder):
    """Coordinator sends the sub-query with the peer's slice list and sums
    remote + local counts."""
    c = new_cluster(2)
    holder.create_index("i").create_frame("f")
    # Make local data on the slices owned by node 0.
    local_slices = c.owns_slices("i", 2, c.nodes[0].host)
    remote_slices = [s for s in range(3) if s not in local_slices]
    f = holder.frame("i", "f")
    for s in local_slices:
        f.set_bit(VIEW_STANDARD, 10, s * SLICE_WIDTH + 1)
    # Grow max_slice so the executor fans out over slices 0..2.
    holder.index("i").set_remote_max_slice(2)

    client = MockClient(lambda index, query, slices, remote: [len(slices or [])])
    e = Executor(
        holder, host=c.nodes[0].host, cluster=c, client_factory=lambda node: client
    )
    (n,) = e.execute("i", parse_string("Count(Bitmap(rowID=10, frame=f))"))
    # local bits + mock's per-slice 1
    assert n == len(local_slices) + len(remote_slices)
    assert client.calls, "remote node should have been queried"
    _, query, slices, remote = client.calls[0]
    assert remote is True
    assert sorted(slices) == sorted(remote_slices)
    assert query == "Count(Bitmap(frame=\"f\", rowID=10))"


def test_remote_failure_fails_over_to_replica(holder):
    """A failed node's slices re-map to replicas (reference:
    executor.go:1186-1197)."""
    c = new_cluster(2)
    c.replica_n = 2  # every slice has both nodes
    holder.create_index("i").create_frame("f")
    f = holder.frame("i", "f")
    for s in range(3):
        f.set_bit(VIEW_STANDARD, 10, s * SLICE_WIDTH + 1)

    def fail(index, query, slices, remote):
        raise ConnectionError("remote down")

    client = MockClient(fail)
    e = Executor(
        holder, host=c.nodes[0].host, cluster=c, client_factory=lambda node: client
    )
    (n,) = e.execute("i", parse_string("Count(Bitmap(rowID=10, frame=f))"))
    assert n == 3  # all slices answered locally via replica failover


def test_remote_unavailable_without_replica(holder):
    c = new_cluster(2)  # replica_n = 1
    holder.create_index("i").create_frame("f")
    holder.index("i").set_remote_max_slice(4)

    def fail(index, query, slices, remote):
        raise ConnectionError("remote down")

    e = Executor(
        holder, host=c.nodes[0].host, cluster=c,
        client_factory=lambda node: MockClient(fail),
    )
    # Fail-fast contract: with no surviving replica the query errors
    # naming exactly the unreachable slices (and the causing error).
    remote = c.owns_slices("i", 4, c.nodes[1].host)
    with pytest.raises(SlicesUnavailableError) as ei:
        e.execute("i", parse_string("Count(Bitmap(rowID=10, frame=f))"))
    assert ei.value.slices == sorted(remote)
    assert "remote down" in str(ei.value)


def test_remote_opt_executes_local_only(holder):
    """opt.remote=True must only touch local slices (reference:
    executor.go:1165-1169)."""
    c = new_cluster(2)
    holder.create_index("i").create_frame("f")
    f = holder.frame("i", "f")
    local = c.owns_slices("i", 3, c.nodes[0].host)
    for s in range(4):
        f.set_bit(VIEW_STANDARD, 10, s * SLICE_WIDTH + 1)

    boom = MockClient(lambda *a: (_ for _ in ()).throw(AssertionError("must not call")))
    e = Executor(holder, host=c.nodes[0].host, cluster=c, client_factory=lambda n: boom)
    (n,) = e.execute(
        "i", parse_string("Count(Bitmap(rowID=10, frame=f))"),
        slices=local, opt=ExecOptions(remote=True),
    )
    assert n == len(local)
    assert not boom.calls


def test_inverse_high_cardinality_past_old_row_cap(ex, holder):
    """An inverse-enabled frame over a high-cardinality slice: one bulk
    import touching 70k distinct columns gives the inverse fragment 70k
    distinct rows — past the old 2^16 dense cap — stored in the sparse
    tier; Bitmap on the inverse view still answers (VERDICT r2 item 4).
    (Budget shrunk so the test exercises the spill without 8 GiB.)"""
    import pilosa_tpu.core.fragment as fr

    idx = holder.create_index("i")
    f = idx.create_frame("f", inverse_enabled=True)
    n = 70_000
    rows = [7] * n + [8]
    cols = list(range(n)) + [999_999]  # row 8's column is outside row 7's range
    inv_frag_budget = 512
    orig_init = fr.Fragment.__init__

    def small_init(self, *a, **kw):
        kw.setdefault("dense_row_budget", inv_frag_budget)
        orig_init(self, *a, **kw)

    # shrink the budget for fragments created during this import
    fr.Fragment.__init__ = small_init
    try:
        f.import_bulk(rows, cols)
    finally:
        fr.Fragment.__init__ = orig_init

    inv = holder.fragment("i", "f", VIEW_INVERSE, 0)
    assert inv is not None
    assert len(inv._sparse) >= n - inv_frag_budget
    assert inv._plane.shape[0] <= inv_frag_budget
    # inverse query: all original rows with the column set
    (bm,) = q(ex, "i", "Bitmap(columnID=999999, frame=f)")
    assert bm.bits() == [8]
    (bm,) = q(ex, "i", "Bitmap(columnID=123, frame=f)")
    assert bm.bits() == [7]
    (bm,) = q(ex, "i", "Bitmap(columnID=69999, frame=f)")
    assert bm.bits() == [7]
    # standard orientation still healthy
    (cnt,) = q(ex, "i", "Count(Bitmap(rowID=7, frame=f))")
    assert cnt == n
    # anti-entropy surface over the tall inverse fragment
    # (70k contiguous rows -> blocks 0..699, plus row 999999's block)
    assert len(inv.blocks()) == n // 100 + 1


# --- assembled leaf-batch cache (VERDICT r2 weak #6 / item 3) ---------------


def test_batch_cache_hit_and_invalidation(ex, holder, monkeypatch):
    """A repeated query reuses the assembled device batch (no per-slice
    re-gather); any fragment write invalidates it via the global write
    epoch; results stay correct."""
    must_set_bits(holder, "i", "f", [(1, 3), (1, SLICE_WIDTH + 7), (2, 3)])

    gathers = []
    orig_dev = Executor._gather_leaf_stacks
    orig_host = Executor._assemble_mesh_batch_host

    def spy_dev(self, index, c, slices):
        gathers.append(str(c))
        return orig_dev(self, index, c, slices)

    def spy_host(self, index, leaves, slices, mesh):
        gathers.append("host")
        return orig_host(self, index, leaves, slices, mesh)

    # Assembly has two entry points (device gather for warm mirrors,
    # host blocks for cold fragments); the cache must avoid BOTH.
    monkeypatch.setattr(Executor, "_gather_leaf_stacks", spy_dev)
    monkeypatch.setattr(Executor, "_assemble_mesh_batch_host", spy_host)

    pql = "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))"
    assert q(ex, "i", pql) == [1]
    assert len(gathers) == 1
    assert q(ex, "i", pql) == [1]          # cache hit: no second gather
    assert len(gathers) == 1
    # Count() strips to its child, so the bare Intersect query shares
    # the same canonical-call entry — batch reused across reduce kinds
    (bm,) = q(ex, "i", "Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f))")
    assert bm.bits() == [3]
    assert len(gathers) == 1
    # a write anywhere bumps the epoch and re-validates -> rebuild
    q(ex, "i", "SetBit(frame=f, rowID=2, columnID=" + str(SLICE_WIDTH + 7) + ")")
    assert q(ex, "i", pql) == [2]
    assert len(gathers) == 2


def test_batch_cache_unrelated_write_revalidates_without_rebuild(ex, holder):
    """A write to an UNRELATED index moves the epoch but the version
    vector still matches — the entry revalidates without re-gathering."""
    must_set_bits(holder, "i", "f", [(1, 3)])
    must_set_bits(holder, "j", "f", [(1, 5)])
    pql = "Count(Bitmap(rowID=1, frame=f))"
    assert q(ex, "i", pql) == [1]
    ent_before = next(iter(ex._batch_cache.values()))["batch"]
    q(ex, "j", 'SetBit(frame=f, rowID=9, columnID=1)')
    assert q(ex, "i", pql) == [1]
    # same batch object reused (revalidated, not rebuilt)
    for key, ent in ex._batch_cache.items():
        if key[0] == "i":
            assert ent["batch"] is ent_before


def test_batch_cache_range_leaves_cached_and_write_invalidated(ex, holder):
    """Range batches cache like Bitmap batches (their validity entries
    carry the quantum + every time-view fragment's version); a write
    into a time view must invalidate them."""
    idx = holder.create_index("i")
    idx.create_frame("f", time_quantum="YMDH")
    q(ex, "i", 'SetBit(frame=f, rowID=1, columnID=2, timestamp="2010-01-01T00:00")')
    pql = ('Count(Range(rowID=1, frame=f, start="2010-01-01T00:00",'
           ' end="2010-12-31T23:59"))')
    assert q(ex, "i", pql) == [1]
    assert any(key[1].find("Range") != -1 for key in ex._batch_cache)
    assert q(ex, "i", pql) == [1]  # warm: served from the cached batch
    q(ex, "i", 'SetBit(frame=f, rowID=1, columnID=7, timestamp="2010-06-15T00:00")')
    assert q(ex, "i", pql) == [2]


def test_batch_cache_range_invalidated_by_quantum_change(ex, holder):
    """set_time_quantum changes which views a Range reads — it bumps
    the write epoch so cached Range batches revalidate."""
    idx = holder.create_index("i")
    f = idx.create_frame("f", time_quantum="YMDH")
    q(ex, "i", 'SetBit(frame=f, rowID=1, columnID=2, timestamp="2010-01-01T00:00")')
    pql = ('Count(Range(rowID=1, frame=f, start="2010-01-01T00:00",'
           ' end="2010-12-31T23:59"))')
    assert q(ex, "i", pql) == [1]
    f.set_time_quantum("Y")
    # The partial-year range can no longer be covered by whole-year
    # views (reference: time.go:95-167 ViewsByTimeRange semantics), so
    # a STALE cached batch returning [1] would be the bug here.
    assert q(ex, "i", pql) == [0]
    year_pql = ('Count(Range(rowID=1, frame=f, start="2010-01-01T00:00",'
                ' end="2011-01-01T00:00"))')
    # The year-aligned range reads the Y view the SetBit fan-out wrote.
    assert q(ex, "i", year_pql) == [1]


def test_batch_cache_invalidated_by_frame_delete(ex, holder):
    """Deleting a frame bumps the write epoch (via fragment close), so
    a cached batch can never serve deleted data (code-review regression,
    r3)."""
    must_set_bits(holder, "i", "f", [(1, 3)])
    pql = "Count(Bitmap(rowID=1, frame=f))"
    assert q(ex, "i", pql) == [1]
    holder.index("i").delete_frame("f")
    with pytest.raises(ExecutorError, match="frame not found"):
        q(ex, "i", pql)
    holder.index("i").create_frame("f")
    assert q(ex, "i", pql) == [0]


def test_concurrent_multislice_topn_and_writes(ex, holder):
    """Parallel MULTI-SLICE src TopN racing writers: the fused scorer
    reads plane SNAPSHOTS captured under each fragment's lock, so every
    result must be internally consistent (sorted, exact after
    quiesce) even while the mirrors refresh under it."""
    import threading

    for s in range(4):
        base = s * SLICE_WIDTH
        for r in range(6):
            must_set_bits(
                holder, "i", "f", [(r, base + c) for c in range(0, 10 + r)]
            )
    errors = []

    def reader():
        try:
            for _ in range(15):
                (pairs,) = q(
                    ex, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=4)"
                )
                counts = [p.count for p in pairs]
                assert counts == sorted(counts, reverse=True)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def writer():
        try:
            for c in range(50, 90):
                q(ex, "i", f"SetBit(frame=f, rowID=2, columnID={c})")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=reader) for _ in range(3)] + [
        threading.Thread(target=writer)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    # Quiesced: exact counts (row0 has 10 cols/slice, all within row0's
    # own columns -> |rowX ∩ row0| = 10 per slice for rows whose column
    # range covers row0's).
    (pairs,) = q(ex, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=6)")
    got = {p.id: p.count for p in pairs}
    assert got[0] == 40  # 10 x 4 slices


def test_concurrent_topn_and_writes(ex, holder):
    """Parallel TopN queries racing writes on the SAME fragment: the
    device score fetch runs outside the fragment lock (core/fragment.py
    top()), so this exercises the snapshot consistency of the gathered
    submatrix under mutation.  Every result must be internally
    consistent (sorted, counts from SOME consistent plane state)."""
    import threading

    for r in range(8):
        must_set_bits(holder, "i", "f", [(r, c) for c in range(0, 40 + r, 2)])
    must_set_bits(holder, "i", "f", [(99, c) for c in range(60)])
    errors = []

    def topn_reader():
        try:
            for _ in range(25):
                (pairs,) = q(
                    ex, "i", "TopN(Bitmap(rowID=99, frame=f), frame=f, n=5)"
                )
                counts = [p.count for p in pairs]
                assert counts == sorted(counts, reverse=True)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def writer(row):
        try:
            for c in range(100, 140):
                q(ex, "i", f"SetBit(frame=f, rowID={row}, columnID={c})")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=topn_reader) for _ in range(3)] + [
        threading.Thread(target=writer, args=(3,)),
        threading.Thread(target=writer, args=(5,)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    # Quiesced: exact final scores.
    (pairs,) = q(ex, "i", "TopN(Bitmap(rowID=99, frame=f), frame=f, n=5)")
    by_id = {p.id: p.count for p in pairs}
    # rows 3 and 5 now have all even cols in [0,40+r) plus [100,140).
    assert by_id[3] == len(set(range(0, 43, 2)) & set(range(60)))
    assert by_id[5] == len(set(range(0, 45, 2)) & set(range(60)))


def test_concurrent_queries_and_writes(ex, holder):
    """Smoke: concurrent queries and writes through one executor (the
    HTTP server is threaded) never crash on the cache paths, and the
    final count is exact."""
    import threading

    must_set_bits(holder, "i", "f", [(1, c) for c in range(50)])
    must_set_bits(holder, "i", "f", [(2, c) for c in range(0, 50, 2)])
    errors = []

    def reader():
        try:
            for _ in range(40):
                (n,) = q(ex, "i",
                         "Count(Intersect(Bitmap(rowID=1, frame=f),"
                         " Bitmap(rowID=2, frame=f)))")
                assert n >= 25
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def writer(base):
        try:
            for c in range(base, base + 40):
                q(ex, "i", f"SetBit(frame=f, rowID=1, columnID={c})")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=reader) for _ in range(3)] + [
        threading.Thread(target=writer, args=(100,)),
        threading.Thread(target=writer, args=(200,)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    (n,) = q(ex, "i", "Count(Bitmap(rowID=1, frame=f))")
    assert n == 50 + 80


@pytest.mark.parametrize(
    "tree",
    [
        "Bitmap(rowID=0, frame=f)",
        "Intersect(Bitmap(rowID=0, frame=f), Bitmap(rowID=1, frame=f))",
        "Union(Bitmap(rowID=0, frame=f), Bitmap(rowID=9, frame=f))",
        "Difference(Bitmap(rowID=0, frame=f), Bitmap(rowID=1, frame=f))",
        "Xor(Bitmap(rowID=9, frame=f), Bitmap(rowID=1, frame=f))",
        "Intersect(Bitmap(rowID=9, frame=f), Bitmap(rowID=1, frame=f))",
        "Difference(Bitmap(rowID=9, frame=f), Bitmap(rowID=1, frame=f))",
        "Union(Intersect(Bitmap(rowID=0, frame=f), Bitmap(rowID=1, frame=f)),"
        " Xor(Bitmap(rowID=2, frame=f), Bitmap(rowID=9, frame=f)))",
    ],
)
def test_eval_expr_np_matches_device(ex, holder, tree):
    """The host (numpy) tree evaluator used for TopN src rows must stay
    bit-identical to the device path — including the None (= absent
    row) propagation rules.  rowID=9 never has bits, so every op's
    empty-operand branch is exercised."""
    import numpy as np

    must_set_bits(holder, "i", "f", [(0, c) for c in range(0, 64, 3)])
    must_set_bits(holder, "i", "f", [(1, c) for c in range(0, 64, 2)])
    must_set_bits(holder, "i", "f", [(2, c) for c in range(5, 40)])

    call = parse_string(tree).calls[0]
    host_rows = ex._eval_tree_slices_host("i", call, [0])
    dev_rows = ex._eval_tree_slices("i", call, [0], "row")

    hr, dr = host_rows[0], dev_rows.get(0)
    if hr is None:
        assert dr is None or not np.asarray(dr).any()
    else:
        want = np.zeros_like(hr) if dr is None else np.asarray(dr)
        np.testing.assert_array_equal(hr, want)


def test_topn_single_slice_skips_phase2(ex, holder, monkeypatch):
    """With one slice, phase-1 TopN scores are already exact and
    complete, so the executor skips the phase-2 refetch (half the
    device round trips); results must equal the two-phase output."""
    must_set_bits(
        holder, "i", "f",
        [(0, c) for c in range(8)] + [(1, c) for c in range(0, 8, 2)]
        + [(2, 1), (2, 2)],
    )
    calls = []
    orig = Executor._execute_topn_slices

    def spy(self, index, c, slices, opt):
        calls.append(str(c))
        return orig(self, index, c, slices, opt)

    monkeypatch.setattr(Executor, "_execute_topn_slices", spy)
    (pairs,) = q(ex, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=2)")
    assert [(p.id, p.count) for p in pairs] == [(0, 8), (1, 4)]
    assert len(calls) == 1  # no phase-2 pass


def test_topn_inverse_orientation(ex, holder):
    """TopN(inverse=true) ranks COLUMNS by row overlap using the
    inverse views' own slice list (reference: executor.go:336-344
    SupportsInverse slice-list swap)."""
    idx = holder.create_index("i")
    idx.create_frame("f", inverse_enabled=True)
    # col 5 appears in rows 0..3; col 9 in rows 0..1; col 2 in row 0.
    for row, col in [(r, 5) for r in range(4)] + [(r, 9) for r in range(2)] + [(0, 2)]:
        q(ex, "i", f"SetBit(frame=f, rowID={row}, columnID={col})")
    (pairs,) = q(ex, "i", "TopN(frame=f, inverse=true, n=2)")
    assert [(p.id, p.count) for p in pairs] == [(5, 4), (9, 2)]
    # src: columns sharing rows with column 5 (all rows 0..3)
    (pairs,) = q(
        ex, "i",
        "TopN(Bitmap(columnID=5, frame=f), frame=f, inverse=true, n=3)",
    )
    assert [(p.id, p.count) for p in pairs] == [(5, 4), (9, 2), (2, 1)]


def test_topn_folded_matches_two_phase(holder):
    """The folded single-round-trip TopN must return exactly what the
    two-phase protocol returns, across random multi-slice data, with and
    without a src bitmap / n / threshold."""
    import numpy as np

    rng = np.random.default_rng(11)
    c = new_cluster(1)
    e = Executor(holder, host=c.nodes[0].host, cluster=c)
    holder.create_index("i").create_frame("f", cache_size=8)
    bits = []
    for s in range(5):
        base = s * SLICE_WIDTH
        for r in range(20):
            for col in rng.integers(0, 200, rng.integers(1, 40)):
                bits.append((r, base + int(col)))
    must_set_bits(holder, "i", "f", bits)

    # Row 90 exists ONLY in slice 0: a src that is absent from the other
    # slices' fragments exercises the short-circuited TopState branch.
    bits2 = [(90, int(c)) for c in rng.integers(0, 200, 30)]
    must_set_bits(holder, "i", "f", bits2)

    # Row attributes for the filters= shape (even rows tagged "a").
    store = holder.frame("i", "f").row_attr_store
    for r in range(0, 20, 2):
        store.set_attrs(r, {"cat": "a"})

    queries = [
        "TopN(frame=f, n=3)",
        "TopN(frame=f)",
        "TopN(Bitmap(rowID=0, frame=f), frame=f, n=4)",
        "TopN(Bitmap(rowID=1, frame=f), frame=f)",
        "TopN(Bitmap(rowID=2, frame=f), frame=f, n=5, threshold=2)",
        "TopN(Bitmap(rowID=0, frame=f), frame=f, n=3, tanimotoThreshold=20)",
        "TopN(Bitmap(rowID=90, frame=f), frame=f, n=4)",
        'TopN(Bitmap(rowID=0, frame=f), frame=f, n=4, field="cat", filters=["a"])',
        'TopN(frame=f, n=3, field="cat", filters=["a"])',
    ]
    for pql in queries:
        (folded,) = q(e, "i", pql)
        # Force the two-phase protocol by pretending not all local.
        orig = Executor._all_slices_local
        Executor._all_slices_local = lambda self, index, slices: False
        try:
            (two_phase,) = q(e, "i", pql)
        finally:
            Executor._all_slices_local = orig
        assert [(p.id, p.count) for p in folded] == [
            (p.id, p.count) for p in two_phase
        ], pql
        if "filters" in pql:
            # Equivalence alone can't catch filters being silently
            # ignored (both paths share the filter code): assert the
            # semantics directly — only tagged (even) rows may appear.
            assert folded, pql
            assert all(p.id % 2 == 0 for p in folded), (pql, folded)


def test_topn_folded_single_device_fetch(holder, monkeypatch):
    """The folded path issues at most ONE jax.device_get for the whole
    query (the two-phase path needs one per phase)."""
    import jax as _jax

    c = new_cluster(1)
    e = Executor(holder, host=c.nodes[0].host, cluster=c)
    holder.create_index("i").create_frame("f")
    bits = []
    for s in range(4):
        base = s * SLICE_WIDTH
        bits += [(r, base + col) for r in range(6) for col in range(0, 50, r + 1)]
    must_set_bits(holder, "i", "f", bits)

    calls = []
    real = _jax.device_get

    def spy(x):
        calls.append(1)
        return real(x)

    monkeypatch.setattr(_jax, "device_get", spy)
    (pairs,) = q(e, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=3)")
    assert pairs
    assert sum(calls) <= 1, f"folded TopN used {sum(calls)} device fetches"


def test_topn_folded_disjoint_caches_guard(holder):
    """Slices whose ranked caches hold disjoint hot rows: the union
    guard must route to the two-phase protocol (no O(S^2) union scoring)
    and results must stay exact."""
    import numpy as np

    c = new_cluster(1)
    e = Executor(holder, host=c.nodes[0].host, cluster=c)
    holder.create_index("i").create_frame("f", cache_size=600)
    bits = []
    # 4 slices x 600 distinct rows each (rows don't overlap across
    # slices), so the union is ~4x any per-slice candidate list.
    for s in range(4):
        base = s * SLICE_WIDTH
        for r in range(s * 600, (s + 1) * 600):
            bits.append((r, base + (r % 100)))
            if r % 3 == 0:
                bits.append((r, base + 200 + (r % 50)))
    must_set_bits(holder, "i", "f", bits)

    calls = []
    orig = Executor._execute_topn_two_phase

    def spy(self, *a, **k):
        calls.append(1)
        return orig(self, *a, **k)

    Executor._execute_topn_two_phase = spy
    try:
        (pairs,) = q(e, "i", "TopN(frame=f, n=5)")
    finally:
        Executor._execute_topn_two_phase = orig
    assert calls, "union guard did not fall back to two-phase"
    assert len(pairs) == 5
    # every returned count must be exact (2 bits for rows % 3 == 0)
    for p in pairs:
        assert p.count == 2


# ---------------------------------------------------------------------------
# cold-start elimination: persistent compile cache + shape pre-warm
# ---------------------------------------------------------------------------


def test_warmup_prewarm_compiles_standard_shapes():
    from pilosa_tpu.exec import warmup
    from pilosa_tpu.parallel import mesh as pmesh

    n = warmup.prewarm(buckets=(1,))
    per_expr = 2  # count + row at bucket 1
    if pmesh.default_slices_mesh() is not None:
        per_expr += 2 * 2  # mesh chunks (1, 2) x (total-count, row)
    # + the fused TopN scorer's smallest bucket shapes (prewarm_topn:
    # row classes x group classes).
    topn = 2
    assert n == len(warmup._STANDARD_EXPRS) * per_expr + topn


def test_enable_compile_cache_idempotent():
    from pilosa_tpu.exec import warmup

    # A stable dir, NOT tmp_path: the cache dir is process-global in
    # JAX, so it must outlive this test or later compiles in the same
    # pytest process would warn on every cache write.
    d = "/tmp/pilosa-tpu-test-compile-cache"
    ok1 = warmup.enable_compile_cache(d)
    # Second call (any dir) is a no-op that still reports active.
    ok2 = warmup.enable_compile_cache(d + "-other")
    assert ok1 and ok2
    # First caller in the PROCESS wins (an earlier test may have won).
    assert warmup.enabled_cache_dir() is not None


# ---------------------------------------------------------------------------
# folded-TopN prep cache (per-query validated, like _cached_batch)
# ---------------------------------------------------------------------------


def _topn_fixture(holder, n_slices=3):
    bits = []
    for s in range(n_slices):
        base = s * SLICE_WIDTH
        bits += [(0, base + i) for i in range(6)]
        bits += [(1, base + i) for i in range(4)]
        bits += [(2, base + i) for i in range(2)]
    must_set_bits(holder, "i", "f", bits)


def test_topn_folded_prep_cache_hits_and_stays_exact(ex, holder, monkeypatch):
    _topn_fixture(holder)
    q_text = "TopN(frame=f, n=2)"
    (p1,) = q(ex, "i", q_text)
    builds = []
    real = type(ex)._topn_folded_build

    def spy(self, index, c, slices):
        builds.append(1)
        return real(self, index, c, slices)

    monkeypatch.setattr(type(ex), "_topn_folded_build", spy)
    (p2,) = q(ex, "i", q_text)
    (p3,) = q(ex, "i", q_text)
    assert builds == []  # warm entry: no rebuild
    assert [(p.id, p.count) for p in p2] == [(p.id, p.count) for p in p1]
    assert [(p.id, p.count) for p in p3] == [(p.id, p.count) for p in p1]


def test_topn_folded_cache_adds_no_staleness_beyond_rank_cache(
    ex, holder, monkeypatch
):
    """After writes, the prep-cached executor must answer identically
    to a BRAND-NEW executor over the same holder (the rank cache's
    throttled re-sort is shared state — the prep cache must add no
    staleness of its own)."""
    from pilosa_tpu.cluster.topology import new_cluster
    from pilosa_tpu.exec import Executor as Ex

    _topn_fixture(holder)
    (before,) = q(ex, "i", "TopN(frame=f, n=3)")
    assert [p.id for p in before] == [0, 1, 2]
    for i in range(10, 20):
        q(ex, "i", f"SetBit(frame=f, rowID=2, columnID={SLICE_WIDTH + i})")
    (cached_after,) = q(ex, "i", "TopN(frame=f, n=3)")
    c = new_cluster(1)
    fresh = Ex(holder, host=c.nodes[0].host, cluster=c)
    (fresh_after,) = q(fresh, "i", "TopN(frame=f, n=3)")
    assert [(p.id, p.count) for p in cached_after] == [
        (p.id, p.count) for p in fresh_after
    ]
    # force the throttled re-sort AND expire the prep entry (its
    # lifetime is bounded by the same interval): fresh counts follow
    holder.fragment("i", "f", "standard", 1).cache.recalculate()
    import pilosa_tpu.core.cache as cache_mod

    monkeypatch.setattr(cache_mod, "RECALCULATE_INTERVAL_S", 0.0)
    (forced,) = q(ex, "i", "TopN(frame=f, n=3)")
    counts = {p.id: p.count for p in forced}
    assert counts[2] == 16 and (forced[0].id, forced[0].count) == (0, 18)


def test_topn_score_single_flight_across_queries(ex, holder, monkeypatch):
    """The folded path scores ONCE per validated prep entry: repeated
    (and concurrent) queries of the same TopN shape reuse the fetched
    count vectors instead of re-dispatching the fused scorer — the
    topn.fetch residual ROADMAP 5 names (165 of 171 ms on the CPU
    smoke).  Results must stay byte-identical."""
    _topn_fixture(holder)
    q_text = "TopN(Bitmap(rowID=0, frame=f), frame=f, n=3)"
    (p1,) = q(ex, "i", q_text)  # builds entry + scores
    scored = []
    real = type(ex)._score_topn_parts

    def spy(self, parts):
        scored.append(1)
        return real(self, parts)

    monkeypatch.setattr(type(ex), "_score_topn_parts", spy)
    (p2,) = q(ex, "i", q_text)
    (p3,) = q(ex, "i", q_text)
    assert scored == []  # shared scores: zero re-dispatch, zero fetch
    assert [(p.id, p.count) for p in p2] == [(p.id, p.count) for p in p1]
    assert [(p.id, p.count) for p in p3] == [(p.id, p.count) for p in p1]


def test_topn_score_storm_shares_launches_and_stays_exact(ex, holder):
    """32 concurrent identical TopN queries: far fewer scorer
    dispatches than queries, every answer identical to sequential."""
    import threading

    _topn_fixture(holder)
    q_text = "TopN(Bitmap(rowID=0, frame=f), frame=f, n=3)"
    (want,) = q(ex, "i", q_text)
    want_pairs = [(p.id, p.count) for p in want]

    scored = []
    real = type(ex)._score_topn_parts
    lock = threading.Lock()

    def spy(self, parts):
        with lock:
            scored.append(1)
        return real(self, parts)

    type(ex)._score_topn_parts = spy
    try:
        results = [None] * 32
        errs = []

        def run(k):
            try:
                (r,) = q(ex, "i", q_text)
                results[k] = [(p.id, p.count) for p in r]
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [
            threading.Thread(target=run, args=(k,)) for k in range(32)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        type(ex)._score_topn_parts = real
    assert not errs
    assert all(r == want_pairs for r in results)
    # Warm entry: the storm shares the already-fetched scores.
    assert len(scored) == 0


def test_topn_score_cache_invalidates_on_write(ex, holder, monkeypatch):
    """A write to a scored fragment rebuilds the entry AND re-scores:
    shared count vectors may never outlive their validity."""
    _topn_fixture(holder)
    q_text = "TopN(frame=f, n=3)"
    (before,) = q(ex, "i", q_text)
    q(ex, "i", f"SetBit(frame=f, rowID=2, columnID={SLICE_WIDTH + 777})")
    scored = []
    real = type(ex)._score_topn_parts

    def spy(self, parts):
        scored.append(1)
        return real(self, parts)

    monkeypatch.setattr(type(ex), "_score_topn_parts", spy)
    (after,) = q(ex, "i", q_text)
    assert scored, "write must force a re-score"
    # No staleness beyond the rank cache's own (documented) throttle:
    # identical to a brand-new executor over the same holder.
    c = new_cluster(1)
    fresh = Executor(holder, host=c.nodes[0].host, cluster=c)
    (fresh_after,) = q(fresh, "i", q_text)
    assert [(p.id, p.count) for p in after] == [
        (p.id, p.count) for p in fresh_after
    ]


def test_topn_folded_cache_invalidates_on_src_frame_write(ex, holder):
    """The src tree's fragments are part of the validity vector: a write
    to the SRC row (same frame here) must re-derive the prep — the
    device-scored counts are exact, so staleness would show directly."""
    _topn_fixture(holder)
    (before,) = q(ex, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=3)")
    c0 = {p.id: p.count for p in before}
    assert c0[1] == 4 * 3  # rows 0/1 overlap on cols 0-3, summed per slice
    assert c0[2] == 2 * 3
    # extend src row 0 AND row 2 with one overlapping new bit in slice 2
    q(ex, "i", f"SetBit(frame=f, rowID=2, columnID={2 * SLICE_WIDTH + 300})")
    q(ex, "i", f"SetBit(frame=f, rowID=0, columnID={2 * SLICE_WIDTH + 300})")
    (after,) = q(ex, "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=3)")
    c1 = {p.id: p.count for p in after}
    assert c1[2] == c0[2] + 1
