"""HBM residency manager tests (pilosa_tpu/device/).

Pool unit tier: byte accounting, LRU victim order, pin leases, the
non-blocking-callback contract.  Integration tier: fragments and the
executor under a budget below total plane bytes — the ISSUE acceptance
scenario (query sweep over more fragments than fit completes correctly,
evictions happen, accounted residency never exceeds budget) — plus the
pending-point-write eviction coherence regression and the /debug/hbm
endpoint on a live server.
"""

import json

import numpy as np
import pytest

from pilosa_tpu import device as device_mod
from pilosa_tpu.cluster.topology import new_cluster
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.device.pool import PlanePool
from pilosa_tpu.exec.executor import Executor
from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.pql.parser import parse_string

MiB = 1 << 20


@pytest.fixture
def fresh_pool():
    """Swap a fresh global pool in for the test (fragments and the
    executor register with the process-global one)."""
    p = PlanePool()
    prev = device_mod._set_pool(p)
    yield p
    device_mod._set_pool(prev)


def budgeted_pool(budget):
    p = PlanePool(budget_bytes=budget)
    return p


# ---------------------------------------------------------------------------
# pool unit tier
# ---------------------------------------------------------------------------


class TestPlanePool:
    def make_entry(self, pool, key, nbytes, dev="dev0", evicted=None):
        def evict():
            if evicted is not None:
                evicted.append((key,))
            return True

        pool.admit((key,), {dev: nbytes}, evict, category="mirror",
                   info={"fragment": key})

    def test_accounting_and_lru_eviction(self):
        pool = budgeted_pool(10 * MiB)
        gone = []
        for i in range(5):
            self.make_entry(pool, f"e{i}", 2 * MiB, evicted=gone)
        assert pool.resident_bytes("dev0") == 10 * MiB
        assert gone == []
        # 6th entry exceeds the budget: the OLDEST entry goes.
        self.make_entry(pool, "e5", 2 * MiB, evicted=gone)
        assert gone == [("e0",)]
        assert pool.resident_bytes("dev0") == 10 * MiB
        assert pool.evictions == 1
        # Touch e1 (now oldest) and admit again: e2 is the victim.
        pool.touch(("e1",))
        self.make_entry(pool, "e6", 2 * MiB, evicted=gone)
        assert gone == [("e0",), ("e2",)]
        # The high-water mark never exceeded budget.
        assert pool.max_resident_bytes("dev0") <= 10 * MiB

    def test_per_device_budgets_are_independent(self):
        pool = budgeted_pool(4 * MiB)
        gone = []
        self.make_entry(pool, "a0", 3 * MiB, dev="devA", evicted=gone)
        self.make_entry(pool, "b0", 3 * MiB, dev="devB", evicted=gone)
        # devB is full but devA has room: only devA entries may be
        # evicted for a devA admission.
        self.make_entry(pool, "a1", 3 * MiB, dev="devA", evicted=gone)
        assert gone == [("a0",)]
        assert pool.resident_bytes("devB") == 3 * MiB

    def test_pinned_entries_never_evicted(self):
        pool = budgeted_pool(4 * MiB)
        gone = []
        self.make_entry(pool, "pinned", 3 * MiB, evicted=gone)
        assert pool.pin(("pinned",))
        for i in range(3):
            self.make_entry(pool, f"f{i}", 3 * MiB, evicted=gone)
        assert ("pinned",) not in gone
        snap = pool.snapshot()
        assert snap["counters"]["overBudget"] > 0  # breach counted, not hidden
        pool.unpin(("pinned",))
        self.make_entry(pool, "final", 3 * MiB, evicted=gone)
        assert ("pinned",) in gone

    def test_refusing_callback_is_skipped(self):
        pool = budgeted_pool(4 * MiB)
        pool.admit(("busy",), {"dev0": 3 * MiB}, lambda: False)
        gone = []
        self.make_entry(pool, "next", 3 * MiB, evicted=gone)
        # The refusing entry stays registered; the breach is counted.
        assert pool.contains(("busy",))
        snap = pool.snapshot()
        assert snap["counters"]["evictSkipped"] >= 1

    def test_resize_and_remove(self):
        pool = budgeted_pool(0)  # unbounded
        pool.admit(("k",), {"dev0": 4 * MiB}, lambda: True, category="sparse")
        pool.resize(("k",), {"dev0": 1 * MiB})
        assert pool.resident_bytes("dev0") == 1 * MiB
        pool.remove(("k",))
        assert pool.resident_bytes("dev0") == 0

    def test_pin_lease_context(self):
        pool = budgeted_pool(0)
        pool.admit(("k",), {"dev0": MiB}, lambda: True)
        with pool.pinned(("k",), None, ("missing",)):
            snap = pool.snapshot()
            (dev,) = snap["devices"]
            assert dev["pinned_bytes"] == MiB
        assert pool.snapshot()["devices"][0]["pinned_bytes"] == 0

    def test_cache_bytes_gauge_tracks_cache_category(self):
        pool = budgeted_pool(0)
        pool.admit(("m",), {"d": 2 * MiB}, lambda: True, category="mirror")
        pool.admit(("c",), {"d": 3 * MiB}, lambda: True, category="cache")
        assert pool.snapshot()["cache_bytes"] == 3 * MiB
        pool.remove(("c",))
        assert pool.snapshot()["cache_bytes"] == 0


# ---------------------------------------------------------------------------
# fragment integration
# ---------------------------------------------------------------------------


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def fill_fragments(holder, n_frags, rows_per_frag=2):
    """One fragment per slice with ``rows_per_frag`` distinct rows set."""
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists("f")
    for s in range(n_frags):
        for r in range(rows_per_frag):
            f.set_bit("standard", r, s * bp.SLICE_WIDTH + r + 1)
            f.set_bit("standard", r, s * bp.SLICE_WIDTH + 100 + r)
    return f


def frags_of(holder, n):
    v = holder.index("i").frame("f").view("standard")
    return [v.fragment(s) for s in range(n)]


class TestFragmentResidency:
    def test_mirror_registers_and_releases_on_close(self, holder, fresh_pool):
        fill_fragments(holder, 1)
        (frag,) = frags_of(holder, 1)
        frag.device_plane()
        assert fresh_pool.resident_bytes() == frag._plane.nbytes
        frag.close()
        assert fresh_pool.resident_bytes() == 0
        assert frag._device is None

    def test_eviction_under_budget_and_rebuild(self, holder, fresh_pool):
        """More mirrors than fit: LRU mirrors evict, every rebuilt plane
        stays correct, accounted residency never exceeds budget."""
        n = 24  # 3 fragments per virtual device (tests force 8 devices)
        fill_fragments(holder, n)
        frags = frags_of(holder, n)
        plane_bytes = frags[0]._plane.nbytes
        # Per-device budget of 2 planes, 3 planes homed per device.
        fresh_pool.configure(budget_bytes=2 * plane_bytes)
        for frag in frags:
            frag.device_plane()
        devs = {bp.home_device(f.slice) for f in frags}
        assert any(
            sum(1 for f in frags if bp.home_device(f.slice) == d) > 2
            for d in devs
        ), "scenario must oversubscribe at least one device"
        assert fresh_pool.evictions > 0
        for d in devs:
            assert fresh_pool.max_resident_bytes(d) <= 2 * plane_bytes
        # Evicted mirrors rebuild correctly on demand.
        for frag in frags:
            row = np.asarray(frag.device_row(0))
            cols = bp.np_row_to_columns(row).tolist()
            assert cols == [1, 100]

    def test_pending_point_write_survives_eviction(self, holder, fresh_pool):
        """Regression: point writes queued against a live mirror, then
        the mirror is evicted BEFORE the next read — the rebuilt plane
        must include the write and must NOT replay the stale pending
        scatter on top of it."""
        fill_fragments(holder, 1)
        (frag,) = frags_of(holder, 1)
        frag.device_plane()
        assert frag.set_bit(0, 7)  # queues a device-pending op
        assert frag._device_pending, "write should queue against the mirror"
        # Evict between the write and the next read.
        assert frag._evict_mirror()
        assert frag._device is None and not frag._device_pending
        cols = bp.np_row_to_columns(np.asarray(frag.device_row(0))).tolist()
        assert cols == [1, 7, 100]
        # And the same through pool pressure instead of a direct call:
        frag.device_plane()
        frag.set_bit(0, 9)
        dev = bp.home_device(frag.slice)
        fresh_pool.configure(budget_bytes=frag._plane.nbytes)
        fresh_pool.admit(
            ("hog",), {dev: frag._plane.nbytes}, lambda: True
        )
        assert frag._device is None, "budget pressure should evict the mirror"
        assert not frag._device_pending
        cols = bp.np_row_to_columns(np.asarray(frag.device_row(0))).tolist()
        assert cols == [1, 7, 9, 100]

    def test_pinned_mirror_survives_pressure(self, holder, fresh_pool):
        fill_fragments(holder, 1)
        (frag,) = frags_of(holder, 1)
        frag.device_plane()
        dev = bp.home_device(frag.slice)
        fresh_pool.configure(budget_bytes=frag._plane.nbytes)
        with fresh_pool.pinned(frag._pool_key):
            fresh_pool.admit(("hog",), {dev: frag._plane.nbytes}, lambda: True)
            assert frag._device is not None, "pinned plane must not drop"


# ---------------------------------------------------------------------------
# executor acceptance scenario (ISSUE: budget below total plane bytes)
# ---------------------------------------------------------------------------


class TestExecutorUnderBudget:
    def test_query_sweep_exceeding_budget(self, holder, fresh_pool):
        n = 24  # three fragments homed per virtual device
        fill_fragments(holder, n)
        frags = frags_of(holder, n)
        plane_bytes = frags[0]._plane.nbytes
        # Per-device budget below one device's three mirrors — and FAR
        # below the holder's total plane bytes.
        budget = int(2.5 * plane_bytes)
        assert budget * 8 < n * plane_bytes
        fresh_pool.configure(budget_bytes=budget)

        c = new_cluster(1)
        ex = Executor(
            holder,
            host=c.nodes[0].host,
            cluster=c,
            prefetcher=device_mod.Prefetcher(pool=fresh_pool),
        )
        try:
            # Per-slice sweep: TopN drives the HBM mirrors (the fused
            # scorer reads resident planes), Count checks exactness.
            for s in range(n):
                (pairs,) = ex.execute(
                    "i",
                    parse_string(
                        "TopN(Bitmap(rowID=0, frame=f), frame=f, n=2)"
                    ),
                    slices=[s],
                )
                got = {p.id: p.count for p in pairs}
                # row0 AND row0 = 2 bits; row1 AND row0 = 0 -> excluded
                assert got == {0: 2}
                (cnt,) = ex.execute(
                    "i",
                    parse_string("Count(Bitmap(rowID=1, frame=f))"),
                    slices=[s],
                )
                assert int(cnt) == 2
            # Sweep again so warm/cold paths both execute.
            for s in range(n):
                (cnt,) = ex.execute(
                    "i",
                    parse_string("Count(Bitmap(rowID=0, frame=f))"),
                    slices=[s],
                )
                assert int(cnt) == 2
        finally:
            ex.close()

        assert fresh_pool.evictions > 0, "sweep must exercise eviction"
        snap = fresh_pool.snapshot()
        for dev in snap["devices"]:
            assert dev["max_resident_bytes"] <= budget, (
                f"resident bytes exceeded budget on {dev['device']}"
            )

    def test_batch_cache_is_byte_evicted(self, holder, fresh_pool):
        """The executor's batch cache is bounded by the pool's BYTES,
        not just its entry count: a budget that fits one assembled
        batch but not two forces LRU eviction between query shapes."""
        fill_fragments(holder, 1)
        # One single-slice batch entry = 1 leaf row = 128 KiB; budget
        # holds one entry, not two.
        fresh_pool.configure(budget_bytes=192 * 1024)
        c = new_cluster(1)
        ex = Executor(holder, host=c.nodes[0].host, cluster=c)
        try:
            q1 = parse_string("Count(Bitmap(rowID=0, frame=f))")
            q2 = parse_string("Count(Bitmap(rowID=1, frame=f))")
            for _ in range(3):
                (n0,) = ex.execute("i", q1, slices=[0])
                (n1,) = ex.execute("i", q2, slices=[0])
                assert int(n0) == 2 and int(n1) == 2
            assert fresh_pool.evictions > 0
            with ex._batch_mu:
                assert len(ex._batch_cache) == 1, (
                    "pool bytes, not the count cap, should bound the cache"
                )
            snap = fresh_pool.snapshot()
            for dev in snap["devices"]:
                assert dev["max_resident_bytes"] <= 192 * 1024
        finally:
            ex.close()


# ---------------------------------------------------------------------------
# prefetcher
# ---------------------------------------------------------------------------


class TestPrefetcher:
    def test_prefetch_warms_cold_mirrors(self, holder, fresh_pool):
        n = 4
        fill_fragments(holder, n)
        frags = frags_of(holder, n)
        pf = device_mod.Prefetcher(pool=fresh_pool)
        scheduled = pf.prefetch(frags, wait=True)
        assert scheduled == n
        assert all(f._device is not None for f in frags)
        snap = fresh_pool.snapshot()
        assert snap["counters"]["prefetchMiss"] == n
        # Second pass: everything already resident.
        assert pf.prefetch(frags, wait=True) == 0
        assert fresh_pool.snapshot()["counters"]["prefetchHit"] == n


# ---------------------------------------------------------------------------
# GET /debug/hbm on a live server
# ---------------------------------------------------------------------------


def test_debug_hbm_endpoint(tmp_path, fresh_pool):
    from pilosa_tpu.net.client import InternalClient
    from pilosa_tpu.net.server import Server

    s = Server(
        data_dir=str(tmp_path / "data"),
        host="127.0.0.1:0",
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        hbm_budget_bytes=64 * MiB,
    )
    s.open()
    try:
        client = InternalClient(s.host, timeout=10.0)
        client.create_index("i")
        client.create_frame("i", "f")
        client.execute_query("i", "SetBit(rowID=0, frame=f, columnID=3)", None)
        client.execute_query(
            "i", "TopN(Bitmap(rowID=0, frame=f), frame=f, n=1)", None
        )
        status, data = client._request("GET", "/debug/hbm")
        assert status == 200
        payload = json.loads(data)
        assert payload["budget_bytes"] == 64 * MiB
        assert payload["devices"], "a queried mirror must be resident"
        dev = payload["devices"][0]
        for field in (
            "device",
            "budget_bytes",
            "resident_bytes",
            "pinned_bytes",
            "max_resident_bytes",
            "entries",
        ):
            assert field in dev
        assert any(
            row.get("fragment") == "i/f/standard/0"
            for row in payload["fragments"]
        ), "per-fragment residency table must list the queried fragment"
        assert "evictions" in payload["counters"]
    finally:
        s.close()
    assert fresh_pool.resident_bytes() == 0, "server close releases HBM"


# ---------------------------------------------------------------------------
# mesh-sharded byte accounting (ISSUE 12: charge each device only its
# shard's bytes; per-shard residency visible in /debug/hbm)
# ---------------------------------------------------------------------------


class TestShardedAccounting:
    def _sharded(self, n_slices=8, words=256):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pilosa_tpu.parallel import mesh as pmesh

        mesh = pmesh.default_slices_mesh()
        assert mesh is not None and mesh.devices.size == 8
        arr = np.zeros((n_slices, 2, words), dtype=np.uint32)
        return (
            jax.device_put(
                arr, NamedSharding(mesh, P(pmesh.AXIS_SLICES, None, None))
            ),
            arr.nbytes,
        )

    def test_sharded_array_charges_per_shard(self):
        sharded, nbytes = self._sharded()
        bbd = device_mod.bytes_by_device(sharded)
        assert len(bbd) == 8, "every mesh device owns a shard"
        assert all(n == nbytes // 8 for n in bbd.values()), bbd
        assert sum(bbd.values()) == nbytes

    def test_replicated_array_charges_full_copy_per_device(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pilosa_tpu.parallel import mesh as pmesh

        mesh = pmesh.default_slices_mesh()
        arr = np.zeros((4, 16), dtype=np.uint32)
        rep = jax.device_put(arr, NamedSharding(mesh, P()))
        bbd = device_mod.bytes_by_device(rep)
        # Each device holds a FULL copy — an even split would
        # under-account 8x.
        assert len(bbd) == 8
        assert all(n == arr.nbytes for n in bbd.values())

    def test_sharded_entry_fits_per_device_budget(self, fresh_pool):
        """The regression the even/global attribution broke: a sharded
        array whose GLOBAL size exceeds the per-device budget — but
        whose per-shard share fits — must admit without evicting
        anything and without an over-budget breach."""
        sharded, nbytes = self._sharded()
        share = nbytes // 8
        fresh_pool.configure(budget_bytes=2 * share)  # global is 8x share
        fresh_pool.admit(
            ("resident",),
            {d: share for d in device_mod.bytes_by_device(sharded)},
            lambda: True,
            category="mirror",
        )
        fresh_pool.admit(
            ("batch",),
            device_mod.bytes_by_device(sharded),
            lambda: True,
            category="cache",
            info={"cache": "batch"},
        )
        snap = fresh_pool.snapshot()
        assert fresh_pool.evictions == 0
        assert snap["counters"]["overBudget"] == 0
        assert fresh_pool.contains(("resident",))
        for dev in snap["devices"]:
            assert dev["resident_bytes"] <= 2 * share
        # /debug/hbm surfaces the per-shard rows.
        batch_rows = [
            row
            for dev in snap["devices"]
            for row in dev["entries"]
            if row.get("cache") == "batch"
        ]
        assert len(batch_rows) == 8
        assert all(row["bytes"] == share for row in batch_rows)
        assert all(
            row.get("sharded") and row.get("shards") == 8
            for row in batch_rows
        )

    def test_executor_sharded_sweep_within_per_device_budget(
        self, holder, fresh_pool
    ):
        """End to end at an artificial per-device budget: an 8-slice
        mesh-sharded Count through the executor — mirrors land on their
        home shards, the assembled global batch charges per shard, and
        no device's reported residency exceeds its budget."""
        n = 8
        fill_fragments(holder, n)
        frags = frags_of(holder, n)
        plane_bytes = frags[0]._plane.nbytes
        # Mirror (1 plane) + the batch entry's shard + zero-row slack
        # fits; the GLOBAL batch (n x 2 leaves x 128 KiB) would not.
        budget = 2 * plane_bytes
        fresh_pool.configure(budget_bytes=budget)
        c = new_cluster(1)
        ex = Executor(holder, host=c.nodes[0].host, cluster=c)
        try:
            (cnt,) = ex.execute(
                "i",
                parse_string(
                    "Count(Intersect(Bitmap(rowID=0, frame=f),"
                    " Bitmap(rowID=1, frame=f)))"
                ),
                slices=list(range(n)),
            )
            assert int(cnt) == 0  # rows 0/1 share no columns per fixture
            (cnt1,) = ex.execute(
                "i",
                parse_string("Count(Bitmap(rowID=0, frame=f))"),
                slices=list(range(n)),
            )
            assert int(cnt1) == 2 * n
            snap = fresh_pool.snapshot()
            assert snap["counters"]["overBudget"] == 0
            assert fresh_pool.evictions == 0
            for dev in snap["devices"]:
                assert dev["max_resident_bytes"] <= budget, dev
        finally:
            ex.close()
