"""The analyzer analyzed: each pass must catch its seeded fixture bug,
the live codebase must be clean modulo the documented allowlist, and
the runtime lock-check wrapper must record orders and flag graphs that
disagree.

Fixture snippets are written into a throwaway ``fixpkg`` package and
indexed directly — no import of the fixture code ever happens (the
analyzer is purely syntactic), so fixtures are free to reference
modules that don't exist.
"""

import textwrap

from pilosa_tpu.analyze import AnalyzeConfig, load_config, run_analysis
from pilosa_tpu.analyze import runtime as rt
from pilosa_tpu.analyze.config import AllowEntry
from pilosa_tpu.analyze.index import PackageIndex
from pilosa_tpu.analyze.locks import LockGraph


def analyze_snippet(tmp_path, source, config=None, passes=("locks", "compile", "resources")):
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    cfg = config or AnalyzeConfig(package="fixpkg")
    idx = PackageIndex(str(pkg), "fixpkg", cfg)
    return run_analysis(config=cfg, passes=passes, index=idx)


def keys(rep, rule=None):
    return [f.key for f in rep.findings if rule is None or f.rule == rule]


# ---------------------------------------------------------------------------
# pass 1: lock order
# ---------------------------------------------------------------------------


def test_lock_cycle_detected(tmp_path):
    rep, graph = analyze_snippet(
        tmp_path,
        """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def f():
            with A:
                with B:
                    pass

        def g():
            with B:
                with A:
                    pass
        """,
    )
    cycles = [f for f in rep.findings if f.rule == "lock-cycle"]
    assert len(cycles) == 1
    assert cycles[0].severity == "error"  # every edge blocking
    assert "fixpkg.mod.A" in cycles[0].key and "fixpkg.mod.B" in cycles[0].key
    assert ("fixpkg.mod.A", "fixpkg.mod.B") in graph.edges
    assert ("fixpkg.mod.B", "fixpkg.mod.A") in graph.edges


def test_interprocedural_cycle_detected(tmp_path):
    rep, graph = analyze_snippet(
        tmp_path,
        """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def helper_b():
            with B:
                pass

        def helper_a():
            with A:
                pass

        def f():
            with A:
                helper_b()

        def g():
            with B:
                helper_a()
        """,
    )
    assert len([f for f in rep.findings if f.rule == "lock-cycle"]) == 1


def test_nonblocking_edge_downgrades_cycle(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def f():
            with A:
                with B:
                    pass

        def g():
            with B:
                if not A.acquire(blocking=False):
                    return
                try:
                    pass
                finally:
                    A.release()
        """,
    )
    cycles = [f for f in rep.findings if f.rule == "lock-cycle"]
    assert len(cycles) == 1
    assert cycles[0].severity == "warn"
    assert "non-blocking" in cycles[0].message


def test_blocking_call_under_lock(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import threading
        import time

        L = threading.Lock()

        def f():
            with L:
                time.sleep(1)
        """,
    )
    ks = keys(rep, "blocking-under-lock")
    assert len(ks) == 1
    assert "sleep" in ks[0] and "fixpkg.mod.L" in ks[0]


def test_blocking_call_reached_through_helper(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import threading

        L = threading.Lock()

        def slow(fut):
            return fut.result(timeout=5)

        def f(fut):
            with L:
                slow(fut)
        """,
    )
    assert any("Future.result" in k for k in keys(rep, "blocking-under-lock"))


def test_condition_wait_under_own_lock_is_exempt(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import threading

        class Q:
            def __init__(self):
                self._mu = threading.Lock()
                self._cv = threading.Condition(self._mu)

            def take(self):
                with self._cv:
                    while True:
                        self._cv.wait()
        """,
    )
    assert keys(rep, "blocking-under-lock") == []


def test_self_deadlock_on_plain_lock(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import threading

        L = threading.Lock()

        def g():
            with L:
                pass

        def f():
            with L:
                g()
        """,
    )
    assert len(keys(rep, "self-deadlock")) == 1


def test_rlock_reentry_is_fine(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import threading

        L = threading.RLock()

        def g():
            with L:
                pass

        def f():
            with L:
                g()
        """,
    )
    assert keys(rep, "self-deadlock") == []
    assert keys(rep, "lock-cycle") == []


# ---------------------------------------------------------------------------
# pass 2: compile hazards
# ---------------------------------------------------------------------------


def test_unbucketed_jit_shape(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import jax.numpy as jnp

        def launch(expr, batch):
            pad = jnp.zeros((batch.shape[0], 8), dtype=batch.dtype)
            full = jnp.concatenate([batch, pad])
            return compiled_batched(expr, "count")(full)
        """,
    )
    assert len(keys(rep, "jit-unbucketed-shape")) == 1


def test_bucketed_dispatch_is_clean(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import jax.numpy as jnp

        def launch(expr, batch):
            bucket = slice_bucket(int(batch.shape[0]))
            pad = jnp.zeros((bucket - batch.shape[0], 8), dtype=batch.dtype)
            full = jnp.concatenate([batch, pad])
            return compiled_batched(expr, "count")(full)
        """,
    )
    assert keys(rep, "jit-unbucketed-shape") == []


def test_fstring_in_compile_key(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        def launch(frame, batch):
            return compiled_batched(f"{frame}-{batch.shape}", "count")(batch)
        """,
    )
    assert len(keys(rep, "jit-key-fstring")) == 1


def test_lru_cache_on_method(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import functools

        class Planner:
            @functools.lru_cache(maxsize=64)
            def plan(self, expr):
                return expr

        @functools.lru_cache
        def fine_module_level(expr):
            return expr
        """,
    )
    ks = keys(rep, "lru-cache-method")
    assert len(ks) == 1
    assert "Planner.plan" in ks[0]


def test_host_sync_in_loop(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        def fetch_all(frags):
            out = []
            for f in frags:
                row = f.device_plane()
                out.append(row.block_until_ready())
            return out
        """,
    )
    assert len(keys(rep, "host-sync-in-loop")) == 1


# ---------------------------------------------------------------------------
# pass 3: resource discipline
# ---------------------------------------------------------------------------


def test_leaked_pin_lease(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        def bad(pool, keys):
            lease = pool.pinned(*keys)
            return 1

        def good(pool, keys):
            with pool.pinned(*keys):
                return 1

        def also_good(pool, keys):
            return pool.pinned(*keys)

        def finally_good(pool, keys):
            lease = pool.pinned(*keys)
            try:
                return 1
            finally:
                lease.release()
        """,
    )
    ks = keys(rep, "leaked-scope")
    assert len(ks) == 1
    assert "fixpkg.mod.bad" in ks[0]


def test_leaked_span(tmp_path):
    rep, _ = analyze_snippet(
        tmp_path,
        """
        def bad(tracer):
            sp = tracer.span("work")
            do_work()
        """,
    )
    assert len(keys(rep, "leaked-scope")) == 1


# ---------------------------------------------------------------------------
# allowlist mechanics
# ---------------------------------------------------------------------------


def test_allowlist_documents_and_goes_stale(tmp_path):
    cfg = AnalyzeConfig(package="fixpkg")
    cfg.allow = [
        AllowEntry(
            rule="blocking-under-lock",
            match="blocking-under-lock:*:sleep",
            reason="test doc",
        ),
        AllowEntry(rule="lock-cycle", match="lock-cycle:does.not.exist*",
                   reason="stale"),
    ]
    rep, _ = analyze_snippet(
        tmp_path,
        """
        import threading
        import time

        L = threading.Lock()

        def f():
            with L:
                time.sleep(1)
        """,
        config=cfg,
    )
    assert rep.active == []
    assert len(rep.allowed) == 1
    assert rep.allowed[0].allowed_by == "test doc"
    assert rep.exit_code() == 0
    assert len(rep.stale_allow) == 1 and "does.not.exist" in rep.stale_allow[0]


# ---------------------------------------------------------------------------
# the live codebase
# ---------------------------------------------------------------------------


def test_live_codebase_clean_modulo_allowlist():
    cfg = load_config()
    rep, graph = run_analysis(config=cfg)
    assert rep.active == [], "\n".join(
        f"{f.rule} {f.location()}: {f.message}" for f in rep.active
    )
    assert rep.stale_allow == [], rep.stale_allow
    # the acceptance bar: the whole-package run stays fast
    assert rep.elapsed_s < 30.0
    # the graph must cover the known design edges (PR-3 pool<->owner)
    assert (
        "pilosa_tpu.core.fragment.Fragment._mu",
        "pilosa_tpu.device.pool.PlanePool._mu",
    ) in graph.edges
    back = graph.edges.get(
        (
            "pilosa_tpu.device.pool.PlanePool._mu",
            "pilosa_tpu.core.fragment.Fragment._mu",
        )
    )
    assert back is not None and back.nonblocking


def test_live_lock_registry_covers_every_creation_site():
    """Every `threading.Lock/RLock/Condition(...)` textually present in
    the package must be in the static registry — otherwise the runtime
    validator would report unknown locks on first use."""
    import re
    import subprocess

    cfg = load_config()
    _, graph = run_analysis(config=cfg, passes=("locks",))
    out = subprocess.run(
        ["grep", "-rn", "-E",
         r"threading\.(Lock|RLock|Condition)\(",
         "pilosa_tpu", "--include=*.py"],
        capture_output=True, text=True, check=True,
    ).stdout
    missing = []
    for line in out.splitlines():
        path, lineno, text = line.split(":", 2)
        if "/analyze/" in path or "__pycache__" in path:
            continue  # the validator itself uses raw factories
        if re.search(r"=\s*threading\.(Lock|RLock|Condition)$", text.strip()):
            continue  # alias assignment, not a creation
        if re.search(r"threading\.Condition\(self\.", text):
            # Condition(self._mu) wraps an EXISTING lock: statically an
            # alias of that lock's site, no new lock at runtime either.
            continue
        if (path, int(lineno)) not in graph.lock_sites:
            missing.append(line)
    assert missing == [], missing


# ---------------------------------------------------------------------------
# runtime validation mode
# ---------------------------------------------------------------------------


def _fake_graph():
    g = LockGraph()
    g.lock_sites = {
        ("pkg/a.py", 10): "pkg.a.A",
        ("pkg/b.py", 20): "pkg.b.B",
        ("pkg/c.py", 30): "pkg.c.C",
    }
    from pilosa_tpu.analyze.locks import Edge

    g.add(Edge("pkg.a.A", "pkg.b.B", False, "pkg/a.py", 11, "t"))
    g.add(Edge("pkg.b.B", "pkg.c.C", False, "pkg/b.py", 21, "t"))
    return g


def test_verify_accepts_direct_and_transitive_orders():
    g = _fake_graph()
    edges = {
        (("pkg/a.py", 10), ("pkg/b.py", 20), False): 3,
        # transitive A -> C: fine, the static order implies it
        (("pkg/a.py", 10), ("pkg/c.py", 30), False): 1,
    }
    sites = set().union(*[{e[0], e[1]} for e in edges])
    assert rt.verify(graph=g, edges=edges, sites=sites) == []


def test_verify_flags_reversed_order_and_unknown_lock():
    g = _fake_graph()
    edges = {(("pkg/b.py", 20), ("pkg/a.py", 10), False): 1}
    sites = {("pkg/b.py", 20), ("pkg/a.py", 10), ("pkg/zz.py", 1)}
    problems = rt.verify(graph=g, edges=edges, sites=sites)
    assert any("no path in the static lock graph" in p for p in problems)
    assert any("never discovered" in p for p in problems)


def test_checked_lock_records_held_order():
    saved_edges = dict(rt._edges)
    saved_created = set(rt._created)
    saved_held = list(rt._held())
    try:
        rt._edges.clear()
        rt._tls.held = []
        a = rt._CheckedLock(rt._real_lock(), ("x/a.py", 1))
        b = rt._CheckedRLock(rt._real_rlock(), ("x/b.py", 2))
        with a:
            with b:
                with b:  # reentrant: no self-edge
                    pass
        assert rt.observed_edges() == {
            (("x/a.py", 1), ("x/b.py", 2), False): 1
        }
        assert rt._held() == []
        # non-blocking acquire records a non-blocking edge
        with b:
            assert a.acquire(blocking=False)
            a.release()
        assert (("x/b.py", 2), ("x/a.py", 1), True) in rt.observed_edges()
    finally:
        rt._edges.clear()
        rt._edges.update(saved_edges)
        rt._created.clear()
        rt._created.update(saved_created)
        rt._tls.held = saved_held


def test_condition_roundtrip_through_checked_lock():
    import threading

    saved_edges = dict(rt._edges)
    try:
        rt._edges.clear()
        rt._tls.held = []
        inner = rt._CheckedRLock(rt._real_rlock(), ("x/cv.py", 7))
        cv = rt._real_condition(inner)
        fired = []

        def waiter():
            with cv:
                while not fired:
                    cv.wait(timeout=5)

        t = threading.Thread(target=waiter)
        t.start()
        with cv:
            fired.append(1)
            cv.notify()
        t.join(timeout=5)
        assert not t.is_alive()
        assert rt._held() == []
    finally:
        rt._edges.clear()
        rt._edges.update(saved_edges)
        rt._tls.held = []
