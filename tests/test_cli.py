"""CLI, config layering, stats clients, and gossip membership."""

import io
import json
import socket
import time

import pytest

from pilosa_tpu import config as config_mod
from pilosa_tpu.cli.main import main
from pilosa_tpu.net.client import InternalClient
from pilosa_tpu.net.server import Server
from pilosa_tpu.obs import stats as stats_mod
from pilosa_tpu.ops.bitplane import SLICE_WIDTH


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


class TestConfig:
    def test_defaults(self):
        cfg = config_mod.Config()
        cfg.validate()
        assert cfg.host == "localhost:10101"
        assert cfg.cluster.replicas == 1
        assert cfg.cluster.type == "static"

    def test_toml_roundtrip(self):
        cfg = config_mod.Config()
        cfg.cluster.hosts = ["a:1", "b:2"]
        cfg.cluster.replicas = 2
        text = cfg.to_toml()
        back = config_mod.from_toml(text)
        assert back.cluster.hosts == ["a:1", "b:2"]
        assert back.cluster.replicas == 2

    def test_unknown_key_rejected(self):
        with pytest.raises(config_mod.ConfigError):
            config_mod.from_toml('bogus-key = "x"\n')
        with pytest.raises(config_mod.ConfigError):
            config_mod.from_toml("[cluster]\nbogus = 1\n")

    def test_env_overlay(self):
        cfg = config_mod.Config()
        config_mod.apply_env(
            cfg,
            {
                "PILOSA_HOST": "h:9",
                "PILOSA_CLUSTER_REPLICAS": "3",
                "PILOSA_CLUSTER_HOSTS": "a:1, b:2",
            },
        )
        assert cfg.host == "h:9"
        assert cfg.cluster.replicas == 3
        assert cfg.cluster.hosts == ["a:1", "b:2"]

    def test_precedence_flag_over_env_over_file(self, tmp_path):
        p = tmp_path / "c.toml"
        p.write_text('host = "file:1"\ndata-dir = "/file"\n')
        cfg = config_mod.load(
            str(p),
            environ={"PILOSA_HOST": "env:2"},
            overrides={"host": "flag:3"},
        )
        assert cfg.host == "flag:3"  # flag wins
        assert cfg.data_dir == "/file"  # file fills the rest

    def test_invalid_cluster_type(self):
        cfg = config_mod.Config()
        cfg.cluster.type = "bogus"
        with pytest.raises(config_mod.ConfigError):
            cfg.validate()


# ---------------------------------------------------------------------------
# CLI against a live server
# ---------------------------------------------------------------------------


@pytest.fixture
def server(tmp_path):
    s = Server(
        data_dir=str(tmp_path / "data"),
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
    )
    s.open()
    c = InternalClient(s.host, timeout=10.0)
    c.create_index("i")
    c.create_frame("i", "f")
    yield s
    s.close()


class TestCLI:
    def test_generate_config(self, capsys):
        assert main(["generate-config"]) == 0
        out = capsys.readouterr().out
        assert "[cluster]" in out
        config_mod.from_toml(out)  # parses clean

    def test_config_command(self, tmp_path, capsys):
        p = tmp_path / "c.toml"
        p.write_text('host = "x:1"\n')
        assert main(["config", "-c", str(p)]) == 0
        assert 'host = "x:1"' in capsys.readouterr().out

    def test_import_export_roundtrip(self, server, tmp_path, capsys):
        csv_in = tmp_path / "in.csv"
        csv_in.write_text("1,10\n1,20\n2,30\n")
        assert (
            main(
                ["import", "--host", server.host, "-i", "i", "-f", "f",
                 str(csv_in)]
            )
            == 0
        )
        out_file = tmp_path / "out.csv"
        assert (
            main(
                ["export", "--host", server.host, "-i", "i", "-f", "f",
                 "-o", str(out_file)]
            )
            == 0
        )
        rows = sorted(
            tuple(map(int, line.split(",")))
            for line in out_file.read_text().strip().splitlines()
        )
        assert rows == [(1, 10), (1, 20), (2, 30)]

    def test_import_with_timestamp(self, server, tmp_path):
        server.holder.frame("i", "f").set_time_quantum("YMD")
        csv_in = tmp_path / "ts.csv"
        csv_in.write_text("1,10,2024-03-05T10:00\n")
        assert (
            main(
                ["import", "--host", server.host, "-i", "i", "-f", "f",
                 str(csv_in)]
            )
            == 0
        )
        c = InternalClient(server.host, timeout=10.0)
        views = c.frame_views("i", "f")
        assert "standard_20240305" in views

    def test_backup_restore(self, server, tmp_path):
        c = InternalClient(server.host, timeout=10.0)
        c.execute_query("i", 'SetBit(frame="f", rowID=4, columnID=44)')
        tar_file = tmp_path / "b.tar"
        assert (
            main(
                ["backup", "--host", server.host, "-i", "i", "-f", "f",
                 "-o", str(tar_file)]
            )
            == 0
        )
        c.delete_index("i")
        c.create_index("i")
        c.create_frame("i", "f")
        assert (
            main(
                ["restore", "--host", server.host, "-i", "i", "-f", "f",
                 "-d", str(tar_file)]
            )
            == 0
        )
        assert c.execute_pql("i", 'Count(Bitmap(frame="f", rowID=4))') == 1

    def test_check_and_inspect(self, server, tmp_path, capsys):
        c = InternalClient(server.host, timeout=10.0)
        c.execute_query("i", 'SetBit(frame="f", rowID=0, columnID=1)')
        frag = server.holder.fragment("i", "f", "standard", 0)
        frag.snapshot()
        data_file = frag.path
        assert main(["check", data_file]) == 0
        assert main(["inspect", data_file]) == 0
        out = capsys.readouterr().out
        assert "containers: 1" in out
        # corrupt file fails check
        bad = tmp_path / "bad"
        bad.write_bytes(b"\x00" * 16)
        assert main(["check", str(bad)]) == 1

    def test_sort(self, tmp_path, capsys, monkeypatch):
        csv_in = tmp_path / "s.csv"
        csv_in.write_text(f"5,{SLICE_WIDTH * 2}\n1,3\n2,{SLICE_WIDTH}\n")
        assert main(["sort", str(csv_in)]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert out == ["1,3", f"2,{SLICE_WIDTH}", f"5,{SLICE_WIDTH * 2}"]

    def test_bench(self, server, capsys):
        assert (
            main(
                ["bench", "--host", server.host, "-i", "i", "-f", "f",
                 "-n", "50"]
            )
            == 0
        )
        assert "op/sec" in capsys.readouterr().out

    def test_bench_query_ops(self, server, capsys):
        """The BASELINE query configs run through the bench CLI:
        intersect-count (configs[1]) and topn (configs[2]) report p50/p95
        against live data."""
        assert (
            main(
                ["bench", "--host", server.host, "-i", "i", "-f", "f",
                 "-n", "30"]
            )
            == 0
        )
        capsys.readouterr()
        assert (
            main(
                ["bench", "--host", server.host, "-i", "i", "-f", "f",
                 "-o", "intersect-count", "-n", "3", "--row1", "1",
                 "--row2", "2"]
            )
            == 0
        )
        out = capsys.readouterr().out
        assert "intersect-count: 3 queries, p50" in out
        assert (
            main(
                ["bench", "--host", server.host, "-i", "i", "-f", "f",
                 "-o", "topn", "-n", "3", "--topn-n", "5"]
            )
            == 0
        )
        out = capsys.readouterr().out
        assert "topn: 3 queries, p50" in out and "pairs" in out

    def test_server_dry_run(self, tmp_path, capsys):
        assert (
            main(
                ["server", "-d", str(tmp_path / "d"), "--bind",
                 "127.0.0.1:0", "--dry-run"]
            )
            == 0
        )


# ---------------------------------------------------------------------------
# stats
# ---------------------------------------------------------------------------


class TestStats:
    def test_expvar_counts_and_tags(self):
        c = stats_mod.ExpvarStatsClient()
        c.count("queries", 2)
        c.count("queries", 3)
        tagged = c.with_tags("index:i", "frame:f")
        tagged.count("queries", 1)
        snap = c.snapshot()
        assert snap["counts"]["queries"] == 5
        assert snap["counts"]["queries[frame:f,index:i]"] == 1

    def test_tag_union_is_sorted_dedup(self):
        c = stats_mod.ExpvarStatsClient().with_tags("b", "a").with_tags("b", "c")
        assert c.tags() == ["a", "b", "c"]

    def test_histogram_snapshot(self):
        c = stats_mod.ExpvarStatsClient()
        for v in (1.0, 2.0, 3.0, 4.0):
            c.histogram("lat", v)
        h = c.snapshot()["histograms"]["lat"]
        assert h["n"] == 4 and h["min"] == 1.0 and h["max"] == 4.0

    def test_statsd_datagram_format(self):
        rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(2.0)
        port = rx.getsockname()[1]
        c = stats_mod.StatsDClient(f"127.0.0.1:{port}").with_tags("index:i")
        c.count("bits", 3)
        data, _ = rx.recvfrom(1024)
        assert data == b"pilosa.bits:3|c|#index:i"
        c.timing("lat", 1.5)
        data, _ = rx.recvfrom(1024)
        assert data == b"pilosa.lat:1.5|ms|#index:i"
        rx.close()

    def test_multi_fanout(self):
        a, b = stats_mod.ExpvarStatsClient(), stats_mod.ExpvarStatsClient()
        m = stats_mod.MultiStatsClient([a, b])
        m.count("x")
        assert a.snapshot()["counts"]["x"] == 1
        assert b.snapshot()["counts"]["x"] == 1

    def test_factory(self):
        assert isinstance(
            stats_mod.new_stats_client("nop"), stats_mod.NopStatsClient
        )
        assert isinstance(
            stats_mod.new_stats_client("expvar"), stats_mod.ExpvarStatsClient
        )
        with pytest.raises(ValueError):
            stats_mod.new_stats_client("bogus")

    def test_server_histograms_reach_debug_vars(self, tmp_path):
        s = Server(
            data_dir=str(tmp_path / "sv"),
            stats=stats_mod.ExpvarStatsClient(),
            anti_entropy_interval=3600, polling_interval=3600,
            cache_flush_interval=3600,
        )
        s.open()
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            status, data = c._request("GET", "/debug/vars")
            snap = json.loads(data)["stats"]
            assert any(k.startswith("http.POST") for k in snap["histograms"])
        finally:
            s.close()

    def test_storage_stats_tag_chain(self, tmp_path):
        """Writes surface as tag-qualified counters/gauges through the
        holder->index->frame->view->slice chain (reference: holder.go:259,
        index.go:443, frame.go:438, view.go:257, fragment.go:412-473)."""
        s = Server(
            data_dir=str(tmp_path / "sv"),
            stats=stats_mod.ExpvarStatsClient(),
            anti_entropy_interval=3600, polling_interval=3600,
            cache_flush_interval=3600,
        )
        s.open()
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            c.create_frame("i", "f")
            c.execute_query("i", 'SetBit(frame="f", rowID=4, columnID=2)')
            c.execute_query("i", 'SetBit(frame="f", rowID=4, columnID=3)')
            c.execute_query("i", 'ClearBit(frame="f", rowID=4, columnID=3)')
            # Reads gauge maxSlice (reference gauges inside MaxSlice()).
            c.execute_pql("i", 'Count(Bitmap(frame="f", rowID=4))')
            status, data = c._request("GET", "/debug/vars")
            snap = json.loads(data)["stats"]
            key = "setBit[frame:f,index:i,slice:0,view:standard]"
            assert snap["counts"].get(key) == 2, snap["counts"]
            assert (
                snap["counts"].get(
                    "clearBit[frame:f,index:i,slice:0,view:standard]"
                )
                == 1
            )
            assert (
                snap["gauges"].get(
                    "rows[frame:f,index:i,slice:0,view:standard]"
                )
                == 4.0
            )
            assert snap["gauges"].get("maxSlice[index:i]") == 0.0
        finally:
            s.close()


# ---------------------------------------------------------------------------
# gossip
# ---------------------------------------------------------------------------


class TestGossip:
    def test_membership_and_user_messages(self):
        from pilosa_tpu.cluster.gossip import GossipNodeSet
        from pilosa_tpu.net import wire_pb2 as wire

        received = []

        class H:
            def receive_message(self, msg):
                received.append(msg)

        a = GossipNodeSet(host="127.0.0.1:1", bind="127.0.0.1:0",
                          gossip_interval=0.05, suspect_after=1.0)
        a.bind = ("127.0.0.1", _free_udp_port())
        a.start(H())
        a.open()
        b = GossipNodeSet(
            host="127.0.0.1:2", bind="127.0.0.1:0",
            seed=f"{a.bind[0]}:{a.bind[1]}",
            gossip_interval=0.05, suspect_after=1.0,
        )
        b.bind = ("127.0.0.1", _free_udp_port())
        b.start(H())
        b.open()
        try:
            deadline = time.time() + 5.0
            while time.time() < deadline:
                if "127.0.0.1:2" in a.nodes() and "127.0.0.1:1" in b.nodes():
                    break
                time.sleep(0.02)
            assert "127.0.0.1:2" in a.nodes()
            assert "127.0.0.1:1" in b.nodes()
            # user message broadcast reaches the peer's handler
            a.send_sync(wire.DeleteIndexMessage(Index="y"))
            deadline = time.time() + 3.0
            while time.time() < deadline and not received:
                time.sleep(0.02)
            assert received and received[0].Index == "y"
        finally:
            a.close()
            b.close()

    def test_send_sync_survives_dropped_datagram(self):
        """send_sync is reliable over lossy UDP: drop the first USER
        datagram on the wire — the ack+retry loop still delivers it,
        synchronously, exactly once (reference analog: reliable TCP
        SendSync, gossip.go:124-149)."""
        from pilosa_tpu.cluster.gossip import GossipNodeSet
        from pilosa_tpu.net import wire_pb2 as wire

        received = []

        class H:
            def receive_message(self, msg):
                received.append(msg)

        a = GossipNodeSet(host="127.0.0.1:1", bind="127.0.0.1:0",
                          gossip_interval=0.05, suspect_after=5.0)
        a.bind = ("127.0.0.1", _free_udp_port())
        a.start(H())
        a.open()
        b = GossipNodeSet(
            host="127.0.0.1:2", bind="127.0.0.1:0",
            seed=f"{a.bind[0]}:{a.bind[1]}",
            gossip_interval=0.05, suspect_after=5.0,
        )
        b.bind = ("127.0.0.1", _free_udp_port())
        b.start(H())
        b.open()

        dropped = []
        orig_send = a._send

        def lossy_send(addr, obj):
            if obj.get("t") == "user" and not dropped:
                dropped.append(obj)  # swallow the first USER datagram
                return
            orig_send(addr, obj)

        a._send = lossy_send
        try:
            deadline = time.time() + 5.0
            while time.time() < deadline:
                if "127.0.0.1:2" in a.nodes() and "127.0.0.1:1" in b.nodes():
                    break
                time.sleep(0.02)
            a.send_sync(wire.DeleteIndexMessage(Index="y"))
            # Reliable send_sync is synchronous: the message was already
            # handled when the call returned, despite the dropped packet.
            assert dropped, "drop injection never triggered"
            assert len(received) == 1 and received[0].Index == "y"
        finally:
            a.close()
            b.close()

    def test_large_state_sync_chunked(self):
        """A schema blob far beyond one UDP datagram (>64 KB) still
        converges: PING advertises only its digest and the receiver
        pulls the blob via STATE-REQ/STATE-CHUNK (VERDICT r2: the
        inline-only path silently stopped syncing at the datagram
        limit)."""
        from pilosa_tpu.cluster.gossip import GossipNodeSet

        blob = bytes(range(256)) * 600  # 150 KB, deterministic
        merged = []
        a = GossipNodeSet(
            host="127.0.0.1:1",
            gossip_interval=0.05,
            suspect_after=5.0,
            state_provider=lambda: blob,
        )
        a.bind = ("127.0.0.1", _free_udp_port())
        a.open()
        b = GossipNodeSet(
            host="127.0.0.1:2",
            seed=f"{a.bind[0]}:{a.bind[1]}",
            gossip_interval=0.05,
            suspect_after=5.0,
            state_merger=merged.append,
        )
        b.bind = ("127.0.0.1", _free_udp_port())
        b.open()
        try:
            deadline = time.time() + 10.0
            while time.time() < deadline and not merged:
                time.sleep(0.02)
            assert merged and merged[0] == blob
            # membership converged too (the big blob never blocked it)
            assert "127.0.0.1:1" in b.nodes()
        finally:
            a.close()
            b.close()

    def test_send_failure_is_logged(self):
        """A failing gossip send (e.g. EMSGSIZE) leaves a log line
        instead of being swallowed (VERDICT r2 weak #5)."""
        from pilosa_tpu.cluster.gossip import GossipNodeSet

        logs = []
        a = GossipNodeSet(
            host="127.0.0.1:1",
            gossip_interval=0.05,
            suspect_after=5.0,
            logger=logs.append,
        )
        a.bind = ("127.0.0.1", _free_udp_port())
        a.open()

        def broken_send(addr, obj):
            raise OSError("Message too long")

        a._send = broken_send
        # give the tick loop a peer to ping
        a._register("127.0.0.1:9", ("127.0.0.1", _free_udp_port()))
        try:
            deadline = time.time() + 5.0
            while time.time() < deadline and not any(
                "failed" in entry for entry in logs
            ):
                time.sleep(0.02)
            assert any(
                "failed" in entry and "Message too long" in entry
                for entry in logs
            ), logs
        finally:
            a.close()

    def test_down_detection(self):
        from pilosa_tpu.cluster.gossip import GossipNodeSet

        a = GossipNodeSet(host="127.0.0.1:1", gossip_interval=0.05,
                          suspect_after=0.3)
        a.bind = ("127.0.0.1", _free_udp_port())
        a.open()
        b = GossipNodeSet(
            host="127.0.0.1:2", seed=f"{a.bind[0]}:{a.bind[1]}",
            gossip_interval=0.05, suspect_after=0.3,
        )
        b.bind = ("127.0.0.1", _free_udp_port())
        b.open()
        try:
            deadline = time.time() + 5.0
            while time.time() < deadline and "127.0.0.1:2" not in a.nodes():
                time.sleep(0.02)
            b.close()
            deadline = time.time() + 5.0
            while time.time() < deadline:
                if a.member_states().get("127.0.0.1:2") == "DOWN":
                    break
                time.sleep(0.05)
            assert a.member_states()["127.0.0.1:2"] == "DOWN"
        finally:
            a.close()


    def test_asymmetric_partition_no_false_down(self):
        """SWIM: drop A<->B datagrams while both still reach C — neither
        A nor B may mark the other DOWN (indirect confirmation via C),
        and C sees both UP throughout (reference surface: memberlist
        indirect probing behind gossip/gossip.go:31-45)."""
        from pilosa_tpu.cluster.gossip import GossipNodeSet

        nodes = []
        for i in range(3):
            n = GossipNodeSet(
                host=f"127.0.0.1:{i + 1}", gossip_interval=0.05,
                suspect_after=0.4,
            )
            n.bind = ("127.0.0.1", _free_udp_port())
            if nodes:
                n.seed = f"{nodes[0].bind[0]}:{nodes[0].bind[1]}"
            nodes.append(n)
        a, b, c = nodes
        for n in nodes:
            n.open()
        try:
            deadline = time.time() + 5.0
            while time.time() < deadline and not all(
                len(n.nodes()) == 3 for n in nodes
            ):
                time.sleep(0.02)
            assert all(len(n.nodes()) == 3 for n in nodes)

            # partition A <-> B, both directions, at the send chokepoint
            def drop_to(node, blocked_addr):
                orig = node._send

                def filtered(addr, obj):
                    if tuple(addr) == tuple(blocked_addr):
                        return
                    orig(addr, obj)

                node._send = filtered

            drop_to(a, b.bind)
            drop_to(b, a.bind)

            # observe for > 5 suspect windows: no false DOWN anywhere
            end = time.time() + 5 * 0.4 + 1.0
            while time.time() < end:
                assert a.member_states().get(b.host) != "DOWN", "A declared B DOWN"
                assert b.member_states().get(a.host) != "DOWN", "B declared A DOWN"
                assert c.member_states().get(a.host) != "DOWN"
                assert c.member_states().get(b.host) != "DOWN"
                time.sleep(0.05)
            # and the NodeSet contract still lists everyone as live
            assert len(a.nodes()) == 3
            assert len(b.nodes()) == 3
            assert len(c.nodes()) == 3
        finally:
            for n in nodes:
                n.close()

    def test_ping_req_relay_legs(self):
        """The 4 SWIM legs individually: with piggyback vouching
        disabled at A, only ping-req -> relay ping -> ack -> ind-ack can
        refresh a partitioned B, so observing B recover from SUSPECT
        proves the relay path end to end."""
        from pilosa_tpu.cluster.gossip import GossipNodeSet

        nodes = []
        for i in range(3):
            n = GossipNodeSet(
                host=f"127.0.0.1:{i + 1}", gossip_interval=0.05,
                suspect_after=0.4,
            )
            n.bind = ("127.0.0.1", _free_udp_port())
            if nodes:
                n.seed = f"{nodes[0].bind[0]}:{nodes[0].bind[1]}"
            nodes.append(n)
        a, b, c = nodes
        for n in nodes:
            n.open()
        try:
            deadline = time.time() + 5.0
            while time.time() < deadline and not all(
                len(n.nodes()) == 3 for n in nodes
            ):
                time.sleep(0.02)
            assert all(len(n.nodes()) == 3 for n in nodes)

            # cut A <-> B and ALSO disable third-party vouching at A, so
            # only an ind-ack can refresh B there
            a._merge_members = lambda members: None
            orig_send = a._send
            ping_reqs = []

            def filtered(addr, obj):
                if obj.get("t") == "ping-req" and obj.get("target") == b.host:
                    ping_reqs.append(obj)
                if tuple(addr) == tuple(b.bind):
                    return
                orig_send(addr, obj)

            a._send = filtered
            orig_b = b._send

            def filtered_b(addr, obj):
                if tuple(addr) == tuple(a.bind):
                    return
                orig_b(addr, obj)

            b._send = filtered_b

            # With vouching off, only the relay (ping-req -> C ping ->
            # B ack -> ind-ack) can refresh B at A.  The SUSPECT window
            # itself is sub-millisecond on localhost (the relay answers
            # instantly), so observe the ping-req side channel instead,
            # and assert B never confirms DOWN.
            end = time.time() + 5 * 0.4 + 2.0
            while time.time() < end:
                assert (
                    a.member_states().get(b.host) != "DOWN"
                ), "relay failed: B declared DOWN"
                time.sleep(0.02)
            assert ping_reqs, "A never issued an indirect probe for B"
            assert a.member_states().get(b.host) in ("UP", "SUSPECT")
        finally:
            for n in nodes:
                n.close()


    def test_send_sync_reaches_suspect_member(self):
        """A SUSPECT member is still live: send_sync must deliver to it
        (a slow-but-reachable node must not silently miss schema
        broadcasts while under suspicion)."""
        from pilosa_tpu.cluster.gossip import GossipNodeSet
        from pilosa_tpu.net import wire_pb2 as wire

        received = []

        class H:
            def receive_message(self, msg):
                received.append(msg)

        a = GossipNodeSet(host="127.0.0.1:1", gossip_interval=0.05,
                          suspect_after=5.0)
        a.bind = ("127.0.0.1", _free_udp_port())
        a.start(H())
        a.open()
        b = GossipNodeSet(
            host="127.0.0.1:2", seed=f"{a.bind[0]}:{a.bind[1]}",
            gossip_interval=0.05, suspect_after=5.0,
        )
        b.bind = ("127.0.0.1", _free_udp_port())
        b.start(H())
        b.open()
        try:
            deadline = time.time() + 5.0
            while time.time() < deadline and "127.0.0.1:2" not in a.nodes():
                time.sleep(0.02)
            assert "127.0.0.1:2" in a.nodes(), "join timed out"
            # Quiesce gossip traffic so nothing can flip the forced
            # state back to UP before send_sync reads it, then force B
            # into SUSPECT at A (as if probes were lost).
            a.gossip_interval = b.gossip_interval = 60.0
            time.sleep(0.15)  # drain in-flight ping/ack datagrams
            with a._mu:
                a._members["127.0.0.1:2"]["state"] = "SUSPECT"
            a.send_sync(wire.DeleteIndexMessage(Index="x"))
            assert received and received[-1].Index == "x"
            with a._mu:
                state = a._members["127.0.0.1:2"]["state"]
            assert state == "SUSPECT", "state flipped mid-test; not exercised"
        finally:
            a.close()
            b.close()


def _free_udp_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _free_tcp_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestGossipCluster:
    """Four real servers joined by UDP gossip: schema replicates through
    gossip broadcast + state piggyback, queries fan out over the
    cluster (the in-process analog of the reference's multi-node server
    tests, server/server_test.go:376-497)."""

    def test_four_node_gossip_cluster(self, tmp_path):
        import time as _time

        from pilosa_tpu.cluster.gossip import GossipNodeSet
        from pilosa_tpu.cluster.topology import Cluster
        from pilosa_tpu.ops.bitplane import SLICE_WIDTH

        n = 4
        gossip_ports = [_free_udp_port() for _ in range(n)]
        # Gossip identity is the HTTP host, so allocate concrete HTTP
        # ports up front (production configs always have them).
        http_hosts = [f"127.0.0.1:{_free_tcp_port()}" for _ in range(n)]
        servers, nodesets, clusters = [], [], []
        for i in range(n):
            ns = GossipNodeSet(
                host=http_hosts[i],
                seed="" if i == 0 else f"127.0.0.1:{gossip_ports[0]}",
                gossip_interval=0.05,
                suspect_after=5.0,
            )
            ns.bind = ("127.0.0.1", gossip_ports[i])
            cluster = Cluster(replica_n=1)
            cluster.node_set = ns
            # placement: every cluster gets the full node list, same order
            for h in sorted(http_hosts):
                cluster.add_node(h)
            s = Server(
                data_dir=str(tmp_path / f"g{i}"),
                host=http_hosts[i],
                cluster=cluster,
                broadcaster=ns,
                broadcast_receiver=ns,
                anti_entropy_interval=3600,
                polling_interval=3600,
                cache_flush_interval=3600,
            )
            servers.append(s)
            nodesets.append(ns)
            clusters.append(cluster)
        try:
            for s in servers:
                s.open()

            # membership converges
            deadline = _time.time() + 10.0
            while _time.time() < deadline:
                if all(len(ns.nodes()) == n for ns in nodesets):
                    break
                _time.sleep(0.05)
            assert all(len(ns.nodes()) == n for ns in nodesets), [
                ns.nodes() for ns in nodesets
            ]

            # schema created on node 0 replicates via gossip broadcast
            c0 = InternalClient(servers[0].host, timeout=10.0)
            c0.create_index("i")
            c0.create_frame("i", "f")
            deadline = _time.time() + 10.0
            while _time.time() < deadline:
                if all(s.holder.frame("i", "f") is not None for s in servers):
                    break
                _time.sleep(0.05)
            assert all(s.holder.frame("i", "f") is not None for s in servers)

            # writes route across the cluster; any node answers the count
            for sl in range(8):
                c0.execute_query(
                    "i",
                    f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH})',
                )
            deadline = _time.time() + 10.0
            want = None
            while _time.time() < deadline:
                c3 = InternalClient(servers[3].host, timeout=10.0)
                want = c3.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))')
                if want == 8:
                    break
                _time.sleep(0.1)
            assert want == 8
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


class TestTutorialWorkflow:
    def test_chemical_similarity_tanimoto(self, server, tmp_path):
        """The reference's chemical-similarity tutorial shape (reference:
        docs/tutorials.md:333-342): molecule fingerprints imported as
        rows via the CLI CSV path, then Tanimoto-thresholded TopN over
        HTTP — validated against a numpy model."""
        import json as jsonlib
        import urllib.request

        import numpy as np

        rng = np.random.default_rng(42)
        n_mol, n_features = 40, 512
        # each molecule: a random ~25%-dense 512-bit fingerprint
        fp = rng.random((n_mol, n_features)) < 0.25
        fp[7] = fp[3]  # a duplicate molecule: tanimoto 100 with #3
        rows, cols = np.nonzero(fp)
        csv_path = tmp_path / "mol.csv"
        with open(csv_path, "w") as fh:
            for r, c in zip(rows, cols):
                fh.write(f"{r},{c}\n")

        assert (
            main(["import", "--host", server.host, "-i", "i", "-f", "f",
                  str(csv_path)])
            == 0
        )

        # TopN(Bitmap(molecule 3), tanimotoThreshold=70) over HTTP
        q = ("TopN(Bitmap(frame=\"f\", rowID=3), frame=\"f\", n=10,"
             " tanimotoThreshold=70)")
        req = urllib.request.Request(
            f"http://{server.host}/index/i/query", data=q.encode(),
            method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            results = jsonlib.load(resp)["results"][0]
        got = {p["id"]: p["count"] for p in results}

        # numpy oracle: ceil(100*|A&B| / (|A|+|B|-|A&B|)) > 70
        import math
        want = {}
        a = fp[3]
        for m in range(n_mol):
            inter = int((a & fp[m]).sum())
            if inter == 0:
                continue
            union = int(a.sum()) + int(fp[m].sum()) - inter
            if math.ceil(100 * inter / union) > 70:
                want[m] = inter
        assert want and got == want
        assert set(want) >= {3, 7}

    def test_star_trace_workflow(self, tmp_path):
        """The star-trace tutorial end-to-end (docs/tutorials.md §1,
        reference: docs/getting-started.md): custom labels, a
        time-quantum frame and a plain frame, CLI CSV import with
        timestamps, then Intersect / cross-frame TopN / Range over
        HTTP — validated against a Python oracle."""
        import json as jsonlib
        import urllib.request

        s = Server(data_dir=str(tmp_path / "data"))
        s.open()
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("repository", {"columnLabel": "repo_id"})
            c.create_frame(
                "repository", "stargazer",
                {"rowLabel": "stargazer_id", "timeQuantum": "YMD"},
            )
            c.create_frame("repository", "language", {"rowLabel": "language_id"})

            # stars: (user, repo, day); language: (lang, repo)
            stars = [
                (14, 1, "2024-01-05T00:00"), (14, 2, "2024-02-10T00:00"),
                (14, 3, "2024-02-20T00:00"), (14, 5, "2024-03-01T00:00"),
                (19, 2, "2024-01-15T00:00"), (19, 3, "2024-02-11T00:00"),
                (19, 4, "2024-04-01T00:00"),
            ]
            langs = [(0, 1), (0, 2), (1, 3), (1, 4), (2, 5)]
            star_csv = tmp_path / "stars.csv"
            with open(star_csv, "w") as fh:
                for u, r, ts in stars:
                    fh.write(f"{u},{r},{ts}\n")
            lang_csv = tmp_path / "langs.csv"
            with open(lang_csv, "w") as fh:
                for l, r in langs:
                    fh.write(f"{l},{r}\n")
            assert main(["import", "--host", s.host, "-i", "repository",
                         "-f", "stargazer", str(star_csv)]) == 0
            assert main(["import", "--host", s.host, "-i", "repository",
                         "-f", "language", str(lang_csv)]) == 0

            def query(pql):
                req = urllib.request.Request(
                    f"http://{s.host}/index/repository/query",
                    data=pql.encode(), method="POST",
                )
                with urllib.request.urlopen(req) as resp:
                    return jsonlib.load(resp)["results"][0]

            # repos starred by BOTH user 14 and user 19
            both = query(
                'Intersect(Bitmap(frame="stargazer", stargazer_id=14),'
                ' Bitmap(frame="stargazer", stargazer_id=19))'
            )
            assert both["bits"] == [2, 3]

            # most-starred languages among user 14's repos:
            # repos {1,2,3,5} -> lang 0 has {1,2}, lang 1 has {3}, lang 2 has {5}
            top = query(
                'TopN(Bitmap(frame="stargazer", stargazer_id=14),'
                ' frame="language", n=5)'
            )
            assert [(p["id"], p["count"]) for p in top] == [(0, 2), (1, 1), (2, 1)]

            # user 14's stars during February 2024 (time-quantum views)
            feb = query(
                'Range(frame="stargazer", stargazer_id=14,'
                ' start="2024-02-01T00:00", end="2024-03-01T00:00")'
            )
            assert feb["bits"] == [2, 3]
        finally:
            s.close()
