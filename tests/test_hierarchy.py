"""Holder/Index/Frame/View hierarchy tests (parity tier for
holder_test.go / index_test.go / frame_test.go / view_test.go)."""

from datetime import datetime

import pytest

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.names import ValidationError
from pilosa_tpu.core.view import VIEW_INVERSE, VIEW_STANDARD
from pilosa_tpu.ops.bitplane import SLICE_WIDTH


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def reopen(h: Holder) -> Holder:
    h.close()
    h2 = Holder(h.path)
    h2.open()
    return h2


def test_create_index_and_frame(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f")
    assert holder.index("i") is idx
    assert holder.frame("i", "f") is f
    assert holder.frame("i", "missing") is None
    assert holder.frame("missing", "f") is None


def test_name_validation(holder):
    with pytest.raises(ValidationError):
        holder.create_index("UPPER")
    with pytest.raises(ValidationError):
        holder.create_index("1leading-digit")
    idx = holder.create_index("ok-name_2")
    with pytest.raises(ValidationError):
        idx.create_frame("Bad Frame")


def test_row_column_label_collision(holder):
    idx = holder.create_index("i", column_label="thing")
    with pytest.raises(ValidationError):
        idx.create_frame("f", row_label="thing")


def test_set_bit_and_persistence(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    f = idx.create_frame("f")
    f.set_bit(VIEW_STANDARD, 10, 100)
    f.set_bit(VIEW_STANDARD, 10, SLICE_WIDTH + 5)  # second slice
    h2 = reopen(h)
    f2 = h2.frame("i", "f")
    assert f2 is not None
    frag0 = h2.fragment("i", "f", VIEW_STANDARD, 0)
    frag1 = h2.fragment("i", "f", VIEW_STANDARD, 1)
    assert frag0.row(10).bits() == [100]
    assert frag1.row(10).bits() == [SLICE_WIDTH + 5]
    assert idx.name in [i for i in h2.indexes()]
    h2.close()


def test_max_slice(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f")
    assert idx.max_slice() == 0
    f.set_bit(VIEW_STANDARD, 0, 3 * SLICE_WIDTH + 1)
    assert idx.max_slice() == 3
    idx.set_remote_max_slice(7)
    assert idx.max_slice() == 7
    assert holder.max_slices() == {"i": 7}


def test_time_quantum_views(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f", time_quantum="YMD")
    f.set_bit(VIEW_STANDARD, 1, 2, t=datetime(2017, 3, 5))
    views = set(f.views().keys())
    assert views == {
        VIEW_STANDARD, "standard_2017", "standard_201703", "standard_20170305",
    }
    for v in views:
        assert f.view(v).fragment(0).row(1).bits() == [2]


def test_index_default_time_quantum_inherited(holder):
    idx = holder.create_index("i", time_quantum="Y")
    f = idx.create_frame("f")
    assert f.time_quantum == "Y"


def test_inverse_import(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f", inverse_enabled=True)
    f.import_bulk([1, 2], [10, 20])
    std = f.view(VIEW_STANDARD)
    inv = f.view(VIEW_INVERSE)
    assert std.fragment(0).row(1).bits() == [10]
    # inverse has row/col swapped
    assert inv.fragment(0).row(10).bits() == [1]
    assert inv.fragment(0).row(20).bits() == [2]
    assert f.max_inverse_slice() == 0


def test_import_without_inverse_skips_inverse_views(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f")
    f.import_bulk([1], [10])
    assert f.view(VIEW_INVERSE) is None


def test_import_with_time(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f", time_quantum="D")
    f.import_bulk([1], [10], [datetime(2017, 1, 2)])
    assert set(f.views()) == {VIEW_STANDARD, "standard_20170102"}
    assert f.view("standard_20170102").fragment(0).row(1).bits() == [10]
    # bits with timestamps also write the standard view (reference:
    # frame.go:546-549)
    assert f.view(VIEW_STANDARD).fragment(0).row(1).bits() == [10]


def test_import_time_without_quantum_errors(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f")
    with pytest.raises(Exception, match="time quantum"):
        f.import_bulk([1], [10], [datetime(2017, 1, 2)])


def test_delete_frame_and_index(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame("f").set_bit(VIEW_STANDARD, 1, 1)
    idx.delete_frame("f")
    assert idx.frame("f") is None
    h.delete_index("i")
    assert h.index("i") is None
    h2 = reopen(h)
    assert h2.indexes() == {}
    h2.close()


def test_schema(holder):
    idx = holder.create_index("i")
    idx.create_frame("f", cache_type="lru", cache_size=100)
    schema = holder.schema()
    assert schema[0]["name"] == "i"
    assert schema[0]["frames"][0]["name"] == "f"
    assert schema[0]["frames"][0]["cacheType"] == "lru"
    assert schema[0]["frames"][0]["cacheSize"] == 100


def test_on_create_slice_callback(tmp_path):
    events = []
    h = Holder(str(tmp_path / "data"))
    h.on_create_slice = lambda index, view, s: events.append((index, view, s))
    h.open()
    idx = h.create_index("i")
    f = idx.create_frame("f")
    f.set_bit(VIEW_STANDARD, 0, 2 * SLICE_WIDTH)  # creates slice 2
    assert ("i", VIEW_STANDARD, 2) in events
    h.close()


def test_column_attrs(holder):
    idx = holder.create_index("i")
    idx.column_attr_store.set_attrs(5, {"name": "col5"})
    assert idx.column_attr_store.attrs(5) == {"name": "col5"}


def test_frame_meta_persistence(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    idx.create_frame(
        "f", row_label="rid", cache_type="lru", cache_size=9,
        inverse_enabled=True, time_quantum="YM",
    )
    h2 = reopen(h)
    f = h2.frame("i", "f")
    assert f.row_label == "rid"
    assert f.cache_type == "lru"
    assert f.cache_size == 9
    assert f.inverse_enabled is True
    assert f.time_quantum == "YM"
    h2.close()


def test_open_skips_stray_dirs(tmp_path):
    import os
    h = Holder(str(tmp_path / "data"))
    h.open()
    h.create_index("good").create_frame("f")
    h.close()
    os.makedirs(str(tmp_path / "data" / "lost+found"))
    os.makedirs(str(tmp_path / "data" / "good" / "Bad Frame Dir"))
    h2 = Holder(str(tmp_path / "data"))
    h2.open()  # must not raise
    assert sorted(h2.indexes()) == ["good"]
    assert sorted(h2.index("good").frames()) == ["f"]
    h2.close()


def test_warm_device_mirrors_uploads_planes(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f")
    f.set_bit("standard", 1, 5)
    f.set_bit("standard", 2, 9)
    frag = holder.fragment("i", "f", "standard", 0)
    assert frag._device is None
    assert holder.warm_device_mirrors() == 1
    assert frag._device is not None
    # budget of zero warms nothing
    idx2 = holder.create_index("j")
    f2 = idx2.create_frame("f")
    f2.set_bit("standard", 1, 5)
    assert holder.warm_device_mirrors(budget_bytes=0) == 0
