"""Pallas kernels vs pure-XLA formulations: bit-identical counts.

Off-TPU these run the kernels in interpreter mode (small shapes only —
interpret is slow); on TPU the same tests exercise the compiled kernels.
Mirrors the reference's asm-vs-Go equivalence tests
(roaring/assembly_test.go:20-43).
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.ops import kernels


def np_popcount(words):
    return int(np.unpackbits(words.view(np.uint8)).sum())


@pytest.fixture
def rows(rng):
    a = rng.integers(0, 2 ** 32, size=bp.WORDS_PER_SLICE, dtype=np.uint32)
    b = rng.integers(0, 2 ** 32, size=bp.WORDS_PER_SLICE, dtype=np.uint32)
    return a, b


def test_count(rows):
    a, _ = rows
    assert int(kernels.count(a)) == np_popcount(a)


@pytest.mark.parametrize("op,fn", [
    ("and", lambda a, b: a & b),
    ("or", lambda a, b: a | b),
    ("xor", lambda a, b: a ^ b),
    ("andnot", lambda a, b: a & ~b),
])
def test_fused_count(rows, op, fn):
    a, b = rows
    assert int(kernels.fused_count(a, b, op)) == np_popcount(fn(a, b))


# 5 rows exercises the rows-%-8 pure-XLA fallback; 16 rows (two grid
# steps) exercises the tile-aligned Pallas kernel path (interpret mode
# off-TPU, compiled on TPU) — BOTH branches must be bit-exact.
@pytest.mark.parametrize("nrows", [5, 16])
def test_top_counts(rng, nrows):
    plane = rng.integers(0, 2 ** 32, size=(nrows, bp.WORDS_PER_SLICE), dtype=np.uint32)
    src = rng.integers(0, 2 ** 32, size=bp.WORDS_PER_SLICE, dtype=np.uint32)
    got = np.asarray(kernels.top_counts(plane, src))
    for r in range(nrows):
        assert got[r] == np_popcount(plane[r] & src)


# 4 rows falls back to plain XLA; 8 rows runs the Pallas grid kernel.
@pytest.mark.parametrize("nrows", [4, 8])
def test_multi_row_operand(rng, nrows):
    a = rng.integers(0, 2 ** 32, size=(nrows, bp.WORDS_PER_SLICE), dtype=np.uint32)
    b = rng.integers(0, 2 ** 32, size=(nrows, bp.WORDS_PER_SLICE), dtype=np.uint32)
    assert int(kernels.fused_count(a, b, "and")) == np_popcount(a & b)
    assert int(kernels.count(a)) == np_popcount(a)


class TestFusedCountRows:
    """Per-row fused count kernel vs the plain-XLA formulation (the
    asm-vs-Go equivalence tier for the batched Count fast path)."""

    @pytest.mark.parametrize("op,fn", [
        ("and", lambda a, b: a & b),
        ("or", lambda a, b: a | b),
        ("xor", lambda a, b: a ^ b),
        ("andnot", lambda a, b: a & ~b),
    ])
    @pytest.mark.parametrize("nrows", [5, 8])
    def test_matches_xla(self, rng, op, fn, nrows):
        import jax
        import jax.numpy as jnp

        from pilosa_tpu.ops import kernels
        from pilosa_tpu.ops.bitplane import WORDS_PER_SLICE

        a = rng.integers(0, 2**32, size=(nrows, WORDS_PER_SLICE), dtype=np.uint32)
        b = rng.integers(0, 2**32, size=(nrows, WORDS_PER_SLICE), dtype=np.uint32)
        got = np.asarray(kernels.fused_count_rows(jnp.asarray(a), jnp.asarray(b), op))
        want = [np_popcount(fn(a[i], b[i])) for i in range(a.shape[0])]
        np.testing.assert_array_equal(got, np.asarray(want, dtype=np.int32))

    def test_plan_fused_matches_general(self, rng):
        import jax.numpy as jnp

        from pilosa_tpu.exec import plan
        from pilosa_tpu.ops.bitplane import WORDS_PER_SLICE
        from pilosa_tpu.pql.parser import parse_string

        q = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
        expr, _ = plan.decompose(q.calls[0].children[0])
        batch = jnp.asarray(
            rng.integers(0, 2**32, size=(8, 2, WORDS_PER_SLICE), dtype=np.uint32)
        )
        general = plan.compiled_batched(expr, "count", fused=False)(batch)
        fused = plan.compiled_batched(expr, "count", fused=True)(batch)
        np.testing.assert_array_equal(np.asarray(general), np.asarray(fused))
