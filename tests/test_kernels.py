"""Pallas kernels vs pure-XLA formulations: bit-identical counts.

Off-TPU these run the kernels in interpreter mode (small shapes only —
interpret is slow); on TPU the same tests exercise the compiled kernels.
Mirrors the reference's asm-vs-Go equivalence tests
(roaring/assembly_test.go:20-43).
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.ops import kernels


def np_popcount(words):
    return int(np.unpackbits(words.view(np.uint8)).sum())


@pytest.fixture
def rows(rng):
    a = rng.integers(0, 2 ** 32, size=bp.WORDS_PER_SLICE, dtype=np.uint32)
    b = rng.integers(0, 2 ** 32, size=bp.WORDS_PER_SLICE, dtype=np.uint32)
    return a, b


def test_count(rows):
    a, _ = rows
    assert int(kernels.count(a)) == np_popcount(a)


@pytest.mark.parametrize("op,fn", [
    ("and", lambda a, b: a & b),
    ("or", lambda a, b: a | b),
    ("xor", lambda a, b: a ^ b),
    ("andnot", lambda a, b: a & ~b),
])
def test_fused_count(rows, op, fn):
    a, b = rows
    assert int(kernels.fused_count(a, b, op)) == np_popcount(fn(a, b))


def test_top_counts(rng):
    # 5 rows: NOT a multiple of the preferred grid chunk, so the
    # odd-row-count (step-1) path is exercised too
    plane = rng.integers(0, 2 ** 32, size=(5, bp.WORDS_PER_SLICE), dtype=np.uint32)
    src = rng.integers(0, 2 ** 32, size=bp.WORDS_PER_SLICE, dtype=np.uint32)
    got = np.asarray(kernels.top_counts(plane, src))
    for r in range(5):
        assert got[r] == np_popcount(plane[r] & src)


def test_multi_row_operand(rng):
    # fused_count over a whole 4-row plane (flattened)
    a = rng.integers(0, 2 ** 32, size=(4, bp.WORDS_PER_SLICE), dtype=np.uint32)
    b = rng.integers(0, 2 ** 32, size=(4, bp.WORDS_PER_SLICE), dtype=np.uint32)
    assert int(kernels.fused_count(a, b, "and")) == np_popcount(a & b)


class TestFusedCountRows:
    """Per-row fused count kernel vs the plain-XLA formulation (the
    asm-vs-Go equivalence tier for the batched Count fast path)."""

    @pytest.mark.parametrize("op,fn", [
        ("and", lambda a, b: a & b),
        ("or", lambda a, b: a | b),
        ("xor", lambda a, b: a ^ b),
        ("andnot", lambda a, b: a & ~b),
    ])
    def test_matches_xla(self, rng, op, fn):
        import jax
        import jax.numpy as jnp

        from pilosa_tpu.ops import kernels
        from pilosa_tpu.ops.bitplane import WORDS_PER_SLICE

        a = rng.integers(0, 2**32, size=(5, WORDS_PER_SLICE), dtype=np.uint32)
        b = rng.integers(0, 2**32, size=(5, WORDS_PER_SLICE), dtype=np.uint32)
        got = np.asarray(kernels.fused_count_rows(jnp.asarray(a), jnp.asarray(b), op))
        want = [np_popcount(fn(a[i], b[i])) for i in range(a.shape[0])]
        np.testing.assert_array_equal(got, np.asarray(want, dtype=np.int32))

    def test_plan_fused_matches_general(self, rng):
        import jax.numpy as jnp

        from pilosa_tpu.exec import plan
        from pilosa_tpu.ops.bitplane import WORDS_PER_SLICE
        from pilosa_tpu.pql.parser import parse_string

        q = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
        expr, _ = plan.decompose(q.calls[0].children[0])
        batch = jnp.asarray(
            rng.integers(0, 2**32, size=(4, 2, WORDS_PER_SLICE), dtype=np.uint32)
        )
        general = plan.compiled_batched(expr, "count", fused=False)(batch)
        fused = plan.compiled_batched(expr, "count", fused=True)(batch)
        np.testing.assert_array_equal(np.asarray(general), np.asarray(fused))
