"""Cache tests (parity tier for cache.go behaviors)."""

from pilosa_tpu.core import cache as cm


def test_lru_eviction():
    c = cm.LRUCache(max_entries=3)
    for i in range(5):
        c.add(i, i * 10)
    assert c.len() == 3
    assert c.get(0) == 0  # evicted
    assert c.get(4) == 40


def test_lru_top_sorted():
    c = cm.LRUCache(10)
    c.add(1, 5)
    c.add(2, 50)
    c.add(3, 5)
    assert c.top() == [cm.Pair(2, 50), cm.Pair(1, 5), cm.Pair(3, 5)]


def test_rank_cache_ordering_and_ids():
    c = cm.RankCache(10)
    c.add(1, 10)
    c.add(2, 30)
    c.add(3, 20)
    assert [p.id for p in c.top()] == [2, 3, 1]
    assert c.ids() == [1, 2, 3]
    assert c.get(3) == 20
    assert c.get(99) == 0


def test_rank_cache_zero_removes():
    c = cm.RankCache(10)
    c.add(1, 10)
    c.add(1, 0)
    assert c.len() == 0


def test_rank_cache_threshold_pruning():
    c = cm.RankCache(max_entries=10)
    for i in range(12):  # 12 > 10 * 1.1
        c.add(i, i + 1)
    # pruned down to max_entries with a threshold floor
    assert c.len() == 10
    assert c.threshold_value > 0
    floor = c.threshold_value
    # adds below the floor for unknown rows are rejected
    c.add(100, floor - 1)
    assert c.get(100) == 0
    # adds above pass
    c.add(101, floor + 100)
    assert c.get(101) == floor + 100


def test_rank_cache_update_existing_below_threshold():
    c = cm.RankCache(max_entries=10)
    for i in range(12):
        c.add(i, 100 + i)
    present = c.ids()[0]
    c.add(present, 1)  # existing rows may always update
    assert c.get(present) == 1


def test_add_pairs_merge():
    a = [cm.Pair(1, 10), cm.Pair(2, 20)]
    b = [cm.Pair(2, 5), cm.Pair(3, 1)]
    merged = {p.id: p.count for p in cm.add_pairs(a, b)}
    assert merged == {1: 10, 2: 25, 3: 1}


def test_sort_pairs_tiebreak():
    got = cm.sort_pairs([cm.Pair(5, 7), cm.Pair(1, 7), cm.Pair(2, 9)])
    assert [(p.id, p.count) for p in got] == [(2, 9), (1, 7), (5, 7)]


def test_new_cache_dispatch():
    assert isinstance(cm.new_cache("ranked", 10), cm.RankCache)
    assert isinstance(cm.new_cache("lru", 10), cm.LRUCache)


def test_rank_cache_invalidate_is_throttled():
    c = cm.RankCache(10)
    c.add(1, 10)
    assert [p.id for p in c.top()] == [1]
    c.add(2, 99)
    c.invalidate()
    # within the 10s window the stale rankings are served...
    assert [p.id for p in c.top()] == [1]
    # ...and an explicit recalculate forces the re-sort
    c.recalculate()
    assert [p.id for p in c.top()] == [2, 1]
