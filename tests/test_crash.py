"""Hard-crash durability: a REAL server process SIGKILLed mid-write.

The storage engine's promise (reference: fragment.go:379-418 op append,
roaring/roaring.go:622-646 replay) is that everything flushed to the
op-log survives a crash and everything after the last group-commit
boundary is lost cleanly — never a fragment that refuses to load.  This
test boots the actual CLI server in a subprocess, streams SetBit writes
at it over HTTP, SIGKILLs it with writes in flight, then opens the
fragment file the corpse left behind and asserts:

* ``roaring.check`` is clean after open (torn tails repaired),
* the surviving bits are exactly a PREFIX of the write stream (ops are
  appended in order; a crash may truncate, never reorder or corrupt).
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.net.client import InternalClient
from pilosa_tpu.ops import roaring

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _boot_server(tmp_path):
    port = _free_port()
    host = f"127.0.0.1:{port}"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        PYTHONPATH=REPO,
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "pilosa_tpu.cli",
            "server",
            "-d",
            str(tmp_path / "data"),
            "--bind",
            host,
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    client = InternalClient(host)
    deadline = time.time() + 90
    while True:
        try:
            client.schema()
            return proc, client
        except Exception:
            if proc.poll() is not None:
                raise RuntimeError("server died during boot")
            if time.time() > deadline:
                proc.kill()
                raise RuntimeError("server never became ready")
            time.sleep(0.2)


@pytest.mark.parametrize("kill_after", [0.3, 1.2])
def test_sigkill_mid_write_recovers_committed_prefix(tmp_path, kill_after):
    proc, client = _boot_server(tmp_path)
    try:
        client.create_index("i")
        client.create_frame("i", "f")

        sent = 0
        stop = threading.Event()
        first_ack = threading.Event()
        errors: list[Exception] = []

        def writer():
            nonlocal sent
            col = 0
            batch = 200
            while not stop.is_set():
                q = "".join(
                    f'SetBit(frame="f", rowID=1, columnID={c})'
                    for c in range(col, col + batch)
                )
                try:
                    client.execute_query("i", q)
                except Exception as e:  # connection dies at the kill
                    errors.append(e)
                    return
                col += batch
                sent = col
                first_ack.set()

        t = threading.Thread(target=writer)
        t.start()
        # The kill timer starts only once a batch is durably acked —
        # otherwise a slow first round-trip makes `sent == 0` flaky.
        assert first_ack.wait(timeout=60), "first batch never acknowledged"
        time.sleep(kill_after)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=15)
        stop.set()
        t.join(timeout=30)
        assert sent > 0, "no batch was acknowledged before the kill"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=15)

    fpath = tmp_path / "data" / "i" / "f" / "views" / "standard" / "fragments" / "0"
    assert fpath.exists(), "fragment file missing after crash"

    # Reopen exactly as a restarted server would; open() performs any
    # torn-tail repair.
    f = Fragment(str(fpath), "i", "f", "standard", 0)
    f.open()
    bits = f.row(1).bits()
    f.close()

    # Committed bits are a prefix of the monotone write stream: columns
    # 0..K-1 for some K no larger than what was ever sent (+ one batch
    # that may have been mid-application at the kill).
    assert bits == list(range(len(bits))), "recovered bits are not a prefix"
    assert len(bits) <= sent + 200

    # The on-disk file parses clean after recovery.
    assert roaring.check(fpath.read_bytes()) == []


def test_sigkill_then_full_server_reboot_serves_queries(tmp_path):
    """After a hard kill, a fresh server over the same data dir must
    boot and answer queries from the committed state (reference:
    fragment.go:154-242 open-with-replay)."""
    proc, client = _boot_server(tmp_path)
    try:
        client.create_index("i")
        client.create_frame("i", "f")
        q = "".join(
            f'SetBit(frame="f", rowID=1, columnID={c})' for c in range(3000)
        )
        client.execute_query("i", q)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=15)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=15)

    proc2, client2 = _boot_server(tmp_path)
    try:
        # Group commit may have lost a buffered suffix, but whatever is
        # there must be a clean prefix and the server must answer.
        count = client2.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))')
        bm = client2.execute_pql("i", 'Bitmap(frame="f", rowID=1)')
        assert bm.bits() == list(range(count))
    finally:
        proc2.terminate()
        try:
            proc2.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc2.kill()


def test_sigkill_mid_import_stream_leaves_loadable_fragment(tmp_path):
    """Bulk imports bypass the op-log and snapshot via tmp+rename; a
    SIGKILL anywhere in an import stream must leave a fragment that
    opens clean (pre- or post-rename state, never a torn file)."""
    import numpy as np

    proc, client = _boot_server(tmp_path)
    killed = threading.Event()
    try:
        client.create_index("i")
        client.create_frame("i", "f")

        acked = 0
        errors: list[Exception] = []

        def importer():
            nonlocal acked
            rng = np.random.default_rng(3)
            batch = 0
            while not killed.is_set():
                cols = np.unique(
                    rng.integers(0, 1 << 20, 5000, dtype=np.uint64)
                )
                rows = np.full(len(cols), batch % 7, dtype=np.uint64)
                try:
                    client.import_bits("i", "f", 0, (rows, cols))
                except Exception as e:
                    errors.append(e)
                    return
                acked += len(cols)
                batch += 1

        t = threading.Thread(target=importer)
        t.start()
        deadline = time.time() + 30
        while acked == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert acked > 0, "no import batch acknowledged"
        time.sleep(0.4)  # land the kill mid-stream / mid-snapshot
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=15)
        killed.set()
        t.join(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=15)

    fpath = tmp_path / "data" / "i" / "f" / "views" / "standard" / "fragments" / "0"
    assert fpath.exists()
    f = Fragment(str(fpath), "i", "f", "standard", 0)
    f.open()  # repairs any torn tail; must not raise
    assert f.count() >= 0
    f.close()
    assert roaring.check(fpath.read_bytes()) == []
