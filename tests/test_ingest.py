"""Durable ingest subsystem (pilosa_tpu/ingest): WAL framing, group
commit, crash recovery, and the device delta-scatter path.

Crash simulation: while a fragment is open its op-log tail lives in
``_op_buf`` (flushed at 64 KiB or close) — copying the data file + the
``.wal`` segment of a LIVE fragment is therefore exactly the disk image
a ``kill -9`` leaves behind.  Recovery over that image must restore
every durably-logged bit; the ``tools/ingest_smoke.py`` CI pass does the
same with a real SIGKILL'd process.
"""

from __future__ import annotations

import os
import shutil
import threading
import time

import numpy as np
import pytest

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.ingest import scatter as ingest_scatter
from pilosa_tpu.ingest import wal as ingest_wal
from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.ops import roaring


@pytest.fixture
def managed(tmp_path):
    """An IngestManager registered over tmp_path plus a fragment opened
    under it (so Fragment.open attaches a WAL writer)."""
    # The manager owns tmp_path/"data" only, so crash images copied to
    # sibling dirs attach to THEIR OWN manager, not this one.
    mgr = ingest_wal.IngestManager(str(tmp_path / "data"), group_commit_ms=1.0)
    ingest_wal.register_manager(mgr)
    frag = Fragment(str(tmp_path / "data" / "0"), "i", "f", "standard", 0)
    frag.open()
    try:
        yield mgr, frag
    finally:
        frag.close()
        ingest_wal.unregister_manager(mgr)
        mgr.close()


def crash_image(frag, dst_dir):
    """Copy a LIVE fragment's on-disk state (data file + WAL segment):
    what a kill -9 leaves behind — buffered ops and all host state gone."""
    os.makedirs(dst_dir, exist_ok=True)
    dst = os.path.join(dst_dir, os.path.basename(frag.path))
    shutil.copy(frag.path, dst)
    wp = ingest_wal.wal_path(frag.path)
    if os.path.exists(wp):
        shutil.copy(wp, ingest_wal.wal_path(dst))
    return dst


class TestWalFraming:
    def _write(self, path, base, snap_size, frames):
        with open(path, "wb") as fh:
            fh.write(ingest_wal.encode_header(base, snap_size))
            v = base
            for ops in frames:
                payload = b"".join(
                    roaring.encode_op(typ, pos) for typ, pos in ops
                )
                v += len(ops)
                fh.write(ingest_wal.encode_frame(payload, len(ops), v))

    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "seg.wal")
        self._write(p, 7, 123, [
            [(roaring.OP_ADD, 5), (roaring.OP_ADD, 9)],
            [(roaring.OP_REMOVE, 5)],
        ])
        seg = ingest_wal.load_segment(p)
        assert seg is not None and not seg.torn
        assert (seg.base_op_version, seg.snap_size) == (7, 123)
        assert seg.n_ops == 3
        assert seg.end_op_version == 10
        assert [f[0] for f in seg.frames] == [9, 10]
        assert seg.good_bytes == os.path.getsize(p)

    def test_missing_and_corrupt_header(self, tmp_path):
        assert ingest_wal.load_segment(str(tmp_path / "nope.wal")) is None
        p = str(tmp_path / "bad.wal")
        with open(p, "wb") as fh:
            fh.write(b"JUNK" + b"\0" * 20)
        assert ingest_wal.load_segment(p) is None

    def test_torn_tail_stops_at_first_bad_frame(self, tmp_path):
        p = str(tmp_path / "seg.wal")
        self._write(p, 0, 0, [[(roaring.OP_ADD, 1)], [(roaring.OP_ADD, 2)]])
        good = os.path.getsize(p)
        with open(p, "ab") as fh:
            # Half a frame: header promising more bytes than exist.
            fh.write(ingest_wal._FRAME.pack(roaring.OP_SIZE, 1, 3))
            fh.write(b"\x01\x02")
        seg = ingest_wal.load_segment(p)
        assert seg.torn and seg.n_ops == 2
        assert seg.good_bytes == good
        assert seg.problem == "torn frame"

    def test_checksum_reject(self, tmp_path):
        p = str(tmp_path / "seg.wal")
        self._write(p, 0, 0, [[(roaring.OP_ADD, 1)], [(roaring.OP_ADD, 2)]])
        data = bytearray(open(p, "rb").read())
        # Flip one payload bit inside the SECOND frame.
        second = (ingest_wal.HEADER_SIZE + ingest_wal.FRAME_HEADER_SIZE
                  + roaring.OP_SIZE + ingest_wal.DIGEST_SIZE)
        data[second + ingest_wal.FRAME_HEADER_SIZE] ^= 0x40
        open(p, "wb").write(bytes(data))
        seg = ingest_wal.load_segment(p)
        assert seg.torn and seg.n_ops == 1
        assert seg.problem == "frame checksum mismatch"

    def test_version_gap_rejects(self, tmp_path):
        p = str(tmp_path / "seg.wal")
        with open(p, "wb") as fh:
            fh.write(ingest_wal.encode_header(0, 0))
            payload = roaring.encode_op(roaring.OP_ADD, 1)
            # end_op_version 5 after one op from base 0: a gap.
            fh.write(ingest_wal.encode_frame(payload, 1, 5))
        seg = ingest_wal.load_segment(p)
        assert seg.torn and seg.n_ops == 0
        assert seg.problem == "bad frame header"


class TestGroupCommit:
    def test_ack_is_durable(self, managed):
        mgr, frag = managed
        frag.set_bit(3, 17)
        mgr.wait_durable()
        seg = ingest_wal.load_segment(ingest_wal.wal_path(frag.path))
        assert seg.n_ops == 1 and not seg.torn

    def test_32_writers_batch_into_few_fsyncs(self, managed):
        mgr, frag = managed
        threads, writes = 32, 12

        def storm(t):
            for k in range(writes):
                frag.set_bit(t, k)
                mgr.wait_durable()

        ts = [threading.Thread(target=storm, args=(t,))
              for t in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        snap = mgr.snapshot()
        total = threads * writes
        assert snap["totalAppends"] == total
        # The whole point of group commit: concurrent durable writers
        # share fsyncs.  Strictly fewer than one per write, with real
        # batching margin.
        assert 1 <= snap["totalFsyncs"] <= total // 4
        seg = ingest_wal.load_segment(ingest_wal.wal_path(frag.path))
        assert seg.n_ops == total and not seg.torn

    def test_snapshot_truncates_segment(self, managed):
        mgr, frag = managed
        for c in range(8):
            frag.set_bit(1, c)
        mgr.wait_durable()
        frag.snapshot()
        seg = ingest_wal.load_segment(ingest_wal.wal_path(frag.path))
        assert seg.frames == []
        assert seg.base_op_version == 8
        # New writes land in the fresh segment at the new base.
        frag.set_bit(1, 100)
        mgr.wait_durable()
        seg = ingest_wal.load_segment(ingest_wal.wal_path(frag.path))
        assert seg.n_ops == 1 and seg.end_op_version == 9

    def test_write_after_manager_close_degrades(self, managed):
        mgr, frag = managed
        frag.set_bit(0, 1)
        mgr.wait_durable()
        mgr.close()
        # Ack path degrades to pre-WAL durability instead of raising.
        assert frag.set_bit(0, 2)
        assert frag.contains(0, 2)


class TestRecovery:
    def test_replay_restores_acked_bits(self, managed, tmp_path):
        mgr, frag = managed
        bits = [(3, 17), (3, 400), (9, 64), (0, 0)]
        for r, c in bits:
            frag.set_bit(r, c)
        frag.clear_bit(3, 400)
        mgr.wait_durable()
        img = crash_image(frag, str(tmp_path / "crash"))

        mgr2 = ingest_wal.IngestManager(str(tmp_path / "crash"))
        ingest_wal.register_manager(mgr2)
        try:
            f2 = Fragment(img, "i", "f", "standard", 0)
            f2.open()
            try:
                assert f2.contains(3, 17)
                assert f2.contains(9, 64)
                assert f2.contains(0, 0)
                assert not f2.contains(3, 400)
                rep = mgr2._last_replay
                assert rep["walOps"] == 5 and rep["skipped"] == 0
                assert rep["replayed"] == 5
            finally:
                f2.close()
        finally:
            ingest_wal.unregister_manager(mgr2)
            mgr2.close()

    def test_replay_skips_ops_before_snapshot(self, managed, tmp_path):
        mgr, frag = managed
        for c in range(4):
            frag.set_bit(1, c)
        mgr.wait_durable()
        frag.snapshot()  # truncates: base_op_version = 4
        for c in range(4, 7):
            frag.set_bit(1, c)
        mgr.wait_durable()
        img = crash_image(frag, str(tmp_path / "crash"))

        mgr2 = ingest_wal.IngestManager(str(tmp_path / "crash"))
        ingest_wal.register_manager(mgr2)
        try:
            f2 = Fragment(img, "i", "f", "standard", 0)
            f2.open()
            try:
                assert [c for c in range(7) if f2.contains(1, c)] == list(
                    range(7)
                )
                rep = mgr2._last_replay
                # Only the 3 post-snapshot ops were in the segment.
                assert rep["walOps"] == 3 and rep["replayed"] == 3
            finally:
                f2.close()
        finally:
            ingest_wal.unregister_manager(mgr2)
            mgr2.close()

    def test_clean_reopen_replays_nothing(self, tmp_path):
        mgr = ingest_wal.IngestManager(str(tmp_path))
        ingest_wal.register_manager(mgr)
        try:
            path = str(tmp_path / "i" / "0")
            frag = Fragment(path, "i", "f", "standard", 0)
            frag.open()
            for c in range(5):
                frag.set_bit(2, c)
            frag.close()  # flushes the op-log tail + final WAL commit
            f2 = Fragment(path, "i", "f", "standard", 0)
            f2.open()
            try:
                assert all(f2.contains(2, c) for c in range(5))
                rep = mgr._last_replay
                # Every WAL op was already in the data file's op-log.
                assert rep is not None and rep["replayed"] == 0
                assert rep["skipped"] == rep["walOps"]
            finally:
                f2.close()
        finally:
            ingest_wal.unregister_manager(mgr)
            mgr.close()

    def test_torn_tail_replays_verified_prefix(self, managed, tmp_path):
        mgr, frag = managed
        for c in range(6):
            frag.set_bit(5, c)
        mgr.wait_durable()
        img = crash_image(frag, str(tmp_path / "crash"))
        # Tear the copied segment mid-frame (crash during the append).
        wp = ingest_wal.wal_path(img)
        sz = os.path.getsize(wp)
        with open(wp, "r+b") as fh:
            fh.truncate(sz - 10)

        mgr2 = ingest_wal.IngestManager(str(tmp_path / "crash"))
        ingest_wal.register_manager(mgr2)
        try:
            f2 = Fragment(img, "i", "f", "standard", 0)
            f2.open()
            try:
                rep = mgr2._last_replay
                assert rep["torn"] is True
                # The verified prefix replays; the torn frame's ops are
                # exactly the never-acked set.
                present = [c for c in range(6) if f2.contains(5, c)]
                assert len(present) == rep["replayed"]
                assert present == list(range(rep["replayed"]))
            finally:
                f2.close()
        finally:
            ingest_wal.unregister_manager(mgr2)
            mgr2.close()

    def test_stale_segment_discarded(self, managed, tmp_path):
        mgr, frag = managed
        frag.set_bit(1, 1)
        mgr.wait_durable()
        img = crash_image(frag, str(tmp_path / "crash"))
        # Run a snapshot on the crash image while no WAL manager owns it
        # (as if [ingest] wal was toggled off for a maintenance window):
        # the data file's snapshot region is rewritten, so the copied
        # segment's snap_size no longer matches and it must be
        # discarded, not replayed against the wrong base.  Bit (1,1)
        # lived only in the forfeited WAL, so it is gone — the
        # documented cost of snapshotting while detached.
        f_tmp = Fragment(img, "i", "f", "standard", 0)
        f_tmp.open()
        f_tmp.set_bit(8, 8)
        f_tmp.snapshot()
        f_tmp.close()

        mgr2 = ingest_wal.IngestManager(str(tmp_path / "crash"))
        ingest_wal.register_manager(mgr2)
        try:
            f2 = Fragment(img, "i", "f", "standard", 0)
            f2.open()
            try:
                assert mgr2._last_replay is None  # discarded, no replay
                assert f2.contains(8, 8) and not f2.contains(1, 1)
            finally:
                f2.close()
        finally:
            ingest_wal.unregister_manager(mgr2)
            mgr2.close()

    def test_diverged_oplog_discards_segment(self, managed, tmp_path):
        mgr, frag = managed
        frag.set_bit(1, 1)
        mgr.wait_durable()
        img = crash_image(frag, str(tmp_path / "crash"))
        # Write to the crash image while no WAL manager owns it: its
        # data op-log gains ops the WAL never saw, so the segment's op
        # sequence and the data file's diverge.  snap_size still
        # matches (op-log appends don't move the snapshot region), so
        # this exercises the byte-prefix check specifically.
        f_tmp = Fragment(img, "i", "f", "standard", 0)
        f_tmp.open()
        f_tmp.set_bit(8, 8)
        f_tmp.close()  # flushes (8,8) into the data op-log, no WAL

        mgr2 = ingest_wal.IngestManager(str(tmp_path / "crash"))
        ingest_wal.register_manager(mgr2)
        try:
            f2 = Fragment(img, "i", "f", "standard", 0)
            f2.open()
            try:
                assert mgr2._last_replay is None  # discarded, no replay
                assert f2.contains(8, 8) and not f2.contains(1, 1)
            finally:
                f2.close()
        finally:
            ingest_wal.unregister_manager(mgr2)
            mgr2.close()


class TestSnapshotDurability:
    def test_snapshot_fsyncs_file_and_directory(self, tmp_path, monkeypatch):
        """Regression (this PR's bugfix): the snapshot's atomic rename
        is durable only after the *directory* entry is fsynced — a crash
        after rename but before dir sync can resurrect the pre-snapshot
        file."""
        frag = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        frag.open()
        try:
            frag.set_bit(0, 1)
            calls = []
            real = ingest_wal._fsync_dir
            monkeypatch.setattr(
                ingest_wal, "_fsync_dir",
                lambda p: calls.append(p) or real(p),
            )
            fsyncs = []
            real_fsync = os.fsync
            monkeypatch.setattr(
                os, "fsync", lambda fd: fsyncs.append(fd) or real_fsync(fd)
            )
            frag.snapshot()
            assert frag.path in calls, "snapshot skipped the dir fsync"
            assert fsyncs, "snapshot skipped the data-file fsync"
        finally:
            frag.close()


class TestDeltaScatter:
    def _storm(self, frag, rng, rows=4, n=300):
        cols = rng.integers(0, 4096, size=n)
        row_ids = rng.integers(0, rows, size=n)
        ops = rng.integers(0, 2, size=n)
        for r, c, op in zip(row_ids, cols, ops):
            if op:
                frag.set_bit(int(r), int(c))
            else:
                frag.clear_bit(int(r), int(c))

    def test_randomized_storm_byte_identity_vs_invalidate(
        self, tmp_path, rng, monkeypatch
    ):
        """The scatter-applied mirror must be byte-identical to the
        invalidate + full re-upload path across a randomized set/clear
        storm (device reads interleaved so deltas actually fold)."""
        fa = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
        fb = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0)
        fa.open()
        fb.open()
        try:
            for f in (fa, fb):
                f.set_bit(0, 9)
                f.device_plane()  # engage the mirror
            seed = int(rng.integers(0, 1 << 31))
            for chunk in range(6):
                r1 = np.random.default_rng(seed + chunk)
                r2 = np.random.default_rng(seed + chunk)
                monkeypatch.setattr(ingest_scatter, "ENABLED", True)
                self._storm(fa, r1)
                monkeypatch.setattr(ingest_scatter, "ENABLED", False)
                self._storm(fb, r2)
                monkeypatch.setattr(ingest_scatter, "ENABLED", True)
                for row in range(4):
                    a = np.asarray(fa.device_row(row))
                    monkeypatch.setattr(ingest_scatter, "ENABLED", False)
                    b = np.asarray(fb.device_row(row))
                    monkeypatch.setattr(ingest_scatter, "ENABLED", True)
                    np.testing.assert_array_equal(a, b)
            assert fa._device is not None, "scatter path lost the mirror"
            assert fb._device is not None
        finally:
            fa.close()
            fb.close()

    def test_import_bulk_paths_byte_identity(self, tmp_path, rng, monkeypatch):
        fa = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0)
        fb = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0)
        fa.open()
        fb.open()
        try:
            for f in (fa, fb):
                f.set_bit(0, 1)
                f.device_plane()
            rows = rng.integers(0, 3, size=64).tolist()
            cols = rng.integers(0, 2048, size=64).tolist()
            monkeypatch.setattr(ingest_scatter, "ENABLED", True)
            fa.import_bulk(rows, cols)
            monkeypatch.setattr(ingest_scatter, "ENABLED", False)
            fb.import_bulk(rows, cols)
            for row in range(3):
                monkeypatch.setattr(ingest_scatter, "ENABLED", True)
                a = np.asarray(fa.device_row(row))
                monkeypatch.setattr(ingest_scatter, "ENABLED", False)
                b = np.asarray(fb.device_row(row))
                np.testing.assert_array_equal(a, b)
        finally:
            fa.close()
            fb.close()

    def test_untouched_row_read_skips_sync(self, tmp_path):
        """A read of a row the queued deltas DON'T touch serves the
        resident mirror as-is: no scatter launch, no re-stage — the
        ingest-storm-on-other-rows read path."""
        from pilosa_tpu.device import pool
        from pilosa_tpu.exec import plan  # noqa: F401 (warm import)

        frag = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        frag.open()
        try:
            for c in range(0, 512, 5):
                frag.set_bit(1, c)
            frag.set_bit(2, 7)
            before_a = np.asarray(frag.device_row(1))  # stage + sync
            launches0 = ingest_scatter.counters()["launches"]
            restage0 = pool().restage_bytes()
            for c in range(32):
                frag.set_bit(2, 100 + c)  # storm on row 2 only
            a = np.asarray(frag.device_row(1))  # untouched row
            assert ingest_scatter.counters()["launches"] == launches0
            assert pool().restage_bytes() == restage0
            np.testing.assert_array_equal(a, before_a)
            # Reading the STORMED row must sync (one launch) and see
            # every bit.
            b = np.asarray(frag.device_row(2))
            assert ingest_scatter.counters()["launches"] == launches0 + 1
            got = {
                int(w) * 32 + s
                for w, word in enumerate(b)
                for s in range(32)
                if int(word) >> s & 1
            }
            assert got == {7} | {100 + c for c in range(32)}
        finally:
            frag.close()

    def test_committer_applies_scatter_in_background(self, managed):
        """The group-commit tick folds queued deltas into the mirror
        off the read path: after a durable write, the pending queue
        drains without any device read."""
        mgr, frag = managed
        frag.set_bit(0, 3)
        frag.device_plane()  # stage the mirror
        frag.set_bit(0, 99)
        mgr.wait_durable()
        deadline = time.time() + 5.0
        while time.time() < deadline:
            with frag._mu:
                if (
                    not frag._device_pending
                    and frag._device_version == frag._version
                    and frag._device is not None
                ):
                    break
            time.sleep(0.01)
        else:
            raise AssertionError(
                "committer never applied pending scatter: "
                f"pending={len(frag._device_pending)}"
            )
        row = np.asarray(frag.device_row(0))
        assert int(row[3 // 32]) >> (3 % 32) & 1
        assert int(row[99 // 32]) >> (99 % 32) & 1

    def test_fold_last_wins(self):
        # (slot, word, mask, op): set bit 3, clear bit 3, set bit 5 —
        # the fold must cancel per bit with later ops winning.
        pending = [(0, 1, 1 << 3, 1), (0, 1, 1 << 3, 0), (0, 1, 1 << 5, 1)]
        slots, words, or_m, andnot_m = ingest_scatter.fold(pending)
        assert slots.tolist() == [0] and words.tolist() == [1]
        assert or_m.tolist() == [1 << 5]
        assert andnot_m.tolist() == [1 << 3]

    def test_pow2_bucketing_bounds_program_cache(self, tmp_path):
        from pilosa_tpu.exec import plan

        frag = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        frag.open()
        try:
            frag.set_bit(0, 0)
            frag.device_plane()
            for n in (1, 2, 3, 5, 9, 17):
                for c in range(n):
                    frag.set_bit(1, 64 * c)
                frag.device_row(1)
            stats = plan.program_cache_stats()
            bounds = plan.program_cache_bounds()
            assert stats.get("plan.scatter", 0) >= 1
            assert stats["plan.scatter"] <= bounds["plan.scatter"]
        finally:
            frag.close()

    def test_concurrent_reader_sees_atomic_planes(self, tmp_path):
        """A reader racing a set-only storm must only ever observe a
        subset of the final bits (atomic plane versions — never a
        half-applied scatter or a torn mirror)."""
        frag = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0)
        frag.open()
        try:
            frag.set_bit(0, 0)
            frag.device_plane()
            final = {0} | {c for c in range(1, 512, 3)}
            stop = threading.Event()
            bad: list = []

            def reader():
                while not stop.is_set():
                    row = np.asarray(frag.device_row(0))
                    got = set(bp.np_row_to_columns(row).tolist())
                    if not got <= final:
                        bad.append(got - final)
                        return

            t = threading.Thread(target=reader)
            t.start()
            for c in range(1, 512, 3):
                frag.set_bit(0, c)
            stop.set()
            t.join(timeout=30)
            assert not bad, f"reader saw bits outside the final set: {bad[:3]}"
            got = set(
                bp.np_row_to_columns(np.asarray(frag.device_row(0))).tolist()
            )
            assert got == final
        finally:
            frag.close()


class TestConfig:
    def test_ingest_config_roundtrip_and_env(self):
        from pilosa_tpu import config as config_mod
        from pilosa_tpu.config import Config

        cfg = Config()
        assert cfg.ingest.wal is True
        assert cfg.ingest.group_commit_ms == 2.0
        doc = cfg.to_toml()
        assert "[ingest]" in doc
        back = config_mod.from_toml(doc)
        assert back.ingest.group_commit_max == cfg.ingest.group_commit_max

        cfg = config_mod.apply_env(Config(), {
            "PILOSA_INGEST_WAL": "false",
            "PILOSA_INGEST_GROUP_COMMIT_MS": "7.5",
            "PILOSA_INGEST_SCATTER": "0",
            "PILOSA_INGEST_WAL_SEGMENT_BYTES": "65536",
        })
        assert cfg.ingest.wal is False
        assert cfg.ingest.group_commit_ms == 7.5
        assert cfg.ingest.scatter is False
        assert cfg.ingest.wal_segment_bytes == 65536

    def test_validate_rejects_bad_values(self):
        from pilosa_tpu.config import Config, ConfigError

        cfg = Config()
        cfg.ingest.group_commit_ms = -1.0
        with pytest.raises(ConfigError):
            cfg.validate()
        cfg = Config()
        cfg.ingest.group_commit_max = 0
        with pytest.raises(ConfigError):
            cfg.validate()
