"""Cluster topology tests (reference: cluster_test.go)."""

import numpy as np

from pilosa_tpu.cluster import Cluster, Node, fnv64a, jump_hash
from pilosa_tpu.cluster.topology import new_cluster


def test_jump_hash_vectors():
    """Vectors generated from the jump-hash reference C++ code
    (reference: cluster_test.go:77-95)."""
    cases = {
        0: [0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0],
        1: [0, 0, 0, 0, 0, 0, 6, 6, 6, 6, 6, 6, 6, 6, 6, 6, 6, 17, 17],
        0xDEADBEEF: [0, 1, 2, 3, 3, 5, 5, 5, 5, 5, 5, 5, 5, 5, 5, 5, 16, 16, 16],
        0x0DDC0FFEEBADF00D: [0, 1, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 15, 15, 15, 15],
    }
    for key, buckets in cases.items():
        for i, want in enumerate(buckets):
            assert jump_hash(key, i + 1) == want, (key, i + 1)


def test_fnv64a():
    # Standard FNV-1a test vectors.
    assert fnv64a(b"") == 0xCBF29CE484222325
    assert fnv64a(b"a") == 0xAF63DC4C8601EC8C
    assert fnv64a(b"foobar") == 0x85944171F73967E8


def test_partition_range():
    c = new_cluster(3)
    rng = np.random.default_rng(7)
    for _ in range(200):
        index = "idx" + str(rng.integers(0, 100))
        s = int(rng.integers(0, 1 << 32))
        p = c.partition(index, s)
        assert 0 <= p < c.partition_n


def test_partition_nodes_ring():
    """Replicas go around the ring (reference: cluster_test.go:30-50)."""
    c = Cluster(
        nodes=[Node("serverA:1000"), Node("serverB:1000"), Node("serverC:1000")],
        replica_n=2,
    )
    # With jump hash, partition 0 maps deterministically; replica is next.
    owners = c.partition_nodes(0)
    assert len(owners) == 2
    i = c.nodes.index(owners[0])
    assert owners[1] is c.nodes[(i + 1) % 3]


def test_replica_n_clamped():
    c = new_cluster(2)
    c.replica_n = 5
    assert len(c.partition_nodes(0)) == 2
    c.replica_n = 0
    assert len(c.partition_nodes(0)) == 1


def test_owns_slices_partitions_all():
    """Every slice has exactly one primary owner; owns_slices over all
    hosts covers [0, max] exactly once."""
    c = new_cluster(4)
    max_slice = 63
    seen = []
    for h in c.hosts():
        seen.extend(c.owns_slices("i", max_slice, h))
    assert sorted(seen) == list(range(max_slice + 1))


def test_fragment_nodes_stable():
    c = new_cluster(3)
    a = [n.host for n in c.fragment_nodes("i", 0)]
    b = [n.host for n in c.fragment_nodes("i", 0)]
    assert a == b


def test_add_node_sorted_idempotent():
    c = Cluster()
    c.add_node("b:1")
    c.add_node("a:1")
    c.add_node("b:1")
    assert c.hosts() == ["a:1", "b:1"]
