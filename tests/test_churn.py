"""Gossip churn under injected datagram loss and member flapping —
the deterministic tier-1 slice of `make churn-soak` (ROADMAP 3's
paired demand): membership must CONVERGE (every live member sees every
live member) without false-DOWN storms (a reachable member never
confirmed DOWN despite the loss)."""

from __future__ import annotations

import time

from pilosa_tpu.cluster.gossip import GossipNodeSet
from pilosa_tpu.testing import faults
from tests.conftest import free_udp_port

N_NODES = 8
LOSS = 0.20  # seeded per-rule, fully deterministic
INTERVAL = 0.05
SUSPECT = 0.6


def _mk(i: int, port: int, seed_addr: str = "") -> GossipNodeSet:
    ns = GossipNodeSet(
        host=f"127.0.0.1:{9000 + i}",  # HTTP identity (never dialed here)
        seed=seed_addr,
        gossip_interval=INTERVAL,
        suspect_after=SUSPECT,
    )
    ns.bind = ("127.0.0.1", port)
    ns.advertise = ("127.0.0.1", port)
    return ns


def _live_view_converged(nodes: dict[str, GossipNodeSet]) -> bool:
    want = set(nodes)
    return all(set(ns.nodes()) == want for ns in nodes.values())


def test_churn_converges_without_false_down_storm():
    faults.install(f"gossip.send:prob={LOSS},seed=42,mode=drop")
    ports = {i: free_udp_port() for i in range(N_NODES)}
    nodes: dict[str, GossipNodeSet] = {}
    try:
        seed_addr = ""
        for i in range(N_NODES):
            ns = _mk(i, ports[i], seed_addr)
            ns.open()
            if not seed_addr:
                seed_addr = f"127.0.0.1:{ports[i]}"
            nodes[ns.host] = ns

        # Phase 1 — lossy but stable: full membership converges and NO
        # live member is ever confirmed DOWN (SWIM's indirect probes
        # must absorb 20% datagram loss).
        deadline = time.time() + 20.0
        while time.time() < deadline and not _live_view_converged(nodes):
            time.sleep(0.1)
        assert _live_view_converged(nodes), {
            h: ns.nodes() for h, ns in nodes.items()
        }
        t_end = time.time() + 4 * SUSPECT
        while time.time() < t_end:
            for h, ns in nodes.items():
                downs = [
                    m
                    for m, st in ns.member_states().items()
                    if st == "DOWN" and m in nodes
                ]
                assert not downs, (
                    f"false-DOWN storm: {h} marked live members {downs} DOWN"
                )
            time.sleep(0.1)

        # Phase 2 — flap: two members die; the survivors must confirm
        # them DOWN (and only them).
        flapped = sorted(nodes)[-2:]
        flap_ports = {}
        for h in flapped:
            ns = nodes.pop(h)
            flap_ports[h] = ns.bind[1]
            ns.close()
        deadline = time.time() + 20.0
        while time.time() < deadline and not _live_view_converged(nodes):
            time.sleep(0.1)
        assert _live_view_converged(nodes), {
            h: ns.nodes() for h, ns in nodes.items()
        }

        # Phase 3 — rejoin on the same identities: membership heals to
        # the full set again.
        for h in flapped:
            i = int(h.rsplit(":", 1)[1]) - 9000
            ns = _mk(i, flap_ports[h], seed_addr)
            ns.open()
            nodes[h] = ns
        deadline = time.time() + 20.0
        while time.time() < deadline and not _live_view_converged(nodes):
            time.sleep(0.1)
        assert _live_view_converged(nodes), {
            h: ns.nodes() for h, ns in nodes.items()
        }
    finally:
        faults.reset()
        for ns in nodes.values():
            ns.close()
