"""AttrStore tests (parity tier for attr_test.go)."""

import pytest

from pilosa_tpu.core.attr import ATTR_BLOCK_SIZE, AttrStore, diff_blocks


@pytest.fixture
def store(tmp_path):
    s = AttrStore(str(tmp_path / "data"))
    s.open()
    yield s
    s.close()


def test_set_get(store):
    store.set_attrs(1, {"a": "x", "b": 2, "c": True, "d": 1.5})
    assert store.attrs(1) == {"a": "x", "b": 2, "c": True, "d": 1.5}
    assert store.attrs(2) == {}


def test_merge_and_delete(store):
    store.set_attrs(1, {"a": "x", "b": 2})
    store.set_attrs(1, {"b": None, "c": 3})
    assert store.attrs(1) == {"a": "x", "c": 3}


def test_invalid_type(store):
    with pytest.raises(TypeError):
        store.set_attrs(1, {"a": [1, 2]})


def test_persistence(tmp_path):
    s = AttrStore(str(tmp_path / "data"))
    s.open()
    s.set_attrs(7, {"k": "v"})
    s.close()
    s2 = AttrStore(str(tmp_path / "data"))
    s2.open()
    assert s2.attrs(7) == {"k": "v"}
    s2.close()


def test_bulk(store):
    store.set_bulk_attrs({1: {"a": 1}, 2: {"b": 2}, 300: {"c": 3}})
    assert store.attrs(1) == {"a": 1}
    assert store.attrs(300) == {"c": 3}


def test_blocks_and_diff(tmp_path):
    a = AttrStore(str(tmp_path / "a"))
    b = AttrStore(str(tmp_path / "b"))
    a.open()
    b.open()
    for s in (a, b):
        s.set_attrs(1, {"x": 1})
        s.set_attrs(ATTR_BLOCK_SIZE + 5, {"y": 2})
    assert diff_blocks(a.blocks(), b.blocks()) == []
    b.set_attrs(1, {"x": 99})  # diverge block 0
    assert diff_blocks(a.blocks(), b.blocks()) == [0]
    a.set_attrs(5 * ATTR_BLOCK_SIZE, {"z": 1})  # block only on a
    assert diff_blocks(a.blocks(), b.blocks()) == [0, 5]
    # block_data returns the block's attrs
    assert a.block_data(5) == {5 * ATTR_BLOCK_SIZE: {"z": 1}}
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# incremental block checksums (anti-entropy cost, ROADMAP 5a)
# ---------------------------------------------------------------------------


def test_incremental_digests_match_cold_rescan(tmp_path):
    """Write-maintained digests must equal the digests a fresh open's
    full scan computes — including deletes that empty a row/block and
    ids straddling the uint63 sign boundary."""
    s = AttrStore(str(tmp_path / "a"))
    s.open()
    s.set_bulk_attrs({i: {"v": i % 9, "s": str(i)} for i in range(0, 500, 3)})
    s.set_attrs(7, {"v": None, "s": None})  # row 7 -> {}
    for i in range(120, 180, 3):
        s.set_attrs(i, {"v": None, "s": None})  # empty most of block 1
    s.set_attrs((1 << 63) - 1, {"edge": 1})
    s.set_attrs((1 << 63) + 2, {"edge": 2})
    s.set_attrs(2**64 - 1, {"edge": 3})
    warm = s.blocks()
    s.close()
    s.open()  # non-empty table -> lazy full rescan on first blocks()
    assert s.blocks() == warm
    # and the rescanned store keeps maintaining incrementally
    s.set_attrs(11, {"new": True})
    warm2 = s.blocks()
    s.close()
    s.open()
    assert s.blocks() == warm2
    s.close()


def test_blocks_fast_after_bulk_population(tmp_path):
    """The anti-entropy tick cost: blocks() over a store populated
    through writes is O(#blocks), not a full-table SELECT+JSON pass."""
    import time

    s = AttrStore(str(tmp_path / "a"))
    s.open()
    n = 100_000
    for lo in range(0, n, 20_000):
        s.set_bulk_attrs({i: {"v": i} for i in range(lo, lo + 20_000)})
    t0 = time.perf_counter()
    blocks = s.blocks()
    dt_ms = (time.perf_counter() - t0) * 1e3
    assert len(blocks) == n // ATTR_BLOCK_SIZE
    assert dt_ms < 100, f"blocks() took {dt_ms:.1f} ms"
    s.close()


@pytest.mark.slow
def test_blocks_under_100ms_at_1m_attrs(tmp_path):
    """The ROADMAP 5a acceptance number, at full scale."""
    import time

    s = AttrStore(str(tmp_path / "a"))
    s.open()
    n = 1_000_000
    for lo in range(0, n, 50_000):
        s.set_bulk_attrs({i: {"v": i} for i in range(lo, lo + 50_000)})
    t0 = time.perf_counter()
    blocks = s.blocks()
    dt_ms = (time.perf_counter() - t0) * 1e3
    assert len(blocks) == n // ATTR_BLOCK_SIZE
    assert dt_ms < 100, f"blocks() took {dt_ms:.1f} ms"
    s.close()


def test_block_data_streams_by_cursor(tmp_path):
    s = AttrStore(str(tmp_path / "a"))
    s.open()
    s.set_bulk_attrs(
        {i: {"v": i} for i in range(ATTR_BLOCK_SIZE, 2 * ATTR_BLOCK_SIZE)}
    )
    s.set_attrs(ATTR_BLOCK_SIZE + 1, {"v": None})  # emptied row excluded
    data = s.block_data(1)
    assert len(data) == ATTR_BLOCK_SIZE - 1
    assert ATTR_BLOCK_SIZE + 1 not in data
    assert data[ATTR_BLOCK_SIZE] == {"v": ATTR_BLOCK_SIZE}
    s.close()
