"""AttrStore tests (parity tier for attr_test.go)."""

import pytest

from pilosa_tpu.core.attr import ATTR_BLOCK_SIZE, AttrStore, diff_blocks


@pytest.fixture
def store(tmp_path):
    s = AttrStore(str(tmp_path / "data"))
    s.open()
    yield s
    s.close()


def test_set_get(store):
    store.set_attrs(1, {"a": "x", "b": 2, "c": True, "d": 1.5})
    assert store.attrs(1) == {"a": "x", "b": 2, "c": True, "d": 1.5}
    assert store.attrs(2) == {}


def test_merge_and_delete(store):
    store.set_attrs(1, {"a": "x", "b": 2})
    store.set_attrs(1, {"b": None, "c": 3})
    assert store.attrs(1) == {"a": "x", "c": 3}


def test_invalid_type(store):
    with pytest.raises(TypeError):
        store.set_attrs(1, {"a": [1, 2]})


def test_persistence(tmp_path):
    s = AttrStore(str(tmp_path / "data"))
    s.open()
    s.set_attrs(7, {"k": "v"})
    s.close()
    s2 = AttrStore(str(tmp_path / "data"))
    s2.open()
    assert s2.attrs(7) == {"k": "v"}
    s2.close()


def test_bulk(store):
    store.set_bulk_attrs({1: {"a": 1}, 2: {"b": 2}, 300: {"c": 3}})
    assert store.attrs(1) == {"a": 1}
    assert store.attrs(300) == {"c": 3}


def test_blocks_and_diff(tmp_path):
    a = AttrStore(str(tmp_path / "a"))
    b = AttrStore(str(tmp_path / "b"))
    a.open()
    b.open()
    for s in (a, b):
        s.set_attrs(1, {"x": 1})
        s.set_attrs(ATTR_BLOCK_SIZE + 5, {"y": 2})
    assert diff_blocks(a.blocks(), b.blocks()) == []
    b.set_attrs(1, {"x": 99})  # diverge block 0
    assert diff_blocks(a.blocks(), b.blocks()) == [0]
    a.set_attrs(5 * ATTR_BLOCK_SIZE, {"z": 1})  # block only on a
    assert diff_blocks(a.blocks(), b.blocks()) == [0, 5]
    # block_data returns the block's attrs
    assert a.block_data(5) == {5 * ATTR_BLOCK_SIZE: {"z": 1}}
    a.close()
    b.close()
