"""Performance-observability tests (obs/perf.py + device/floorprobe.py
+ the /debug/perf | /debug/profile | /debug/stacks endpoints + the
native latency histogram families).

Covers the PR-17 acceptance bar: per-site roofline accounting visible
at /debug/perf for the direct / coalesce / interp / collective / topn
launch sites with %-of-floor figures; lifetime-monotonic histogram
``_count``/``_sum`` past the reservoir size; StatsD truncation at
UTF-8 codepoint boundaries; /metrics exposition validity under a
concurrent scrape-vs-writer storm; launch byte accounting consistent
with /debug/hbm plane geometry; profiling endpoints end-to-end
including the 501 path; and the telemetry overhead guard (on-vs-off
query p99 within 5%).
"""

from __future__ import annotations

import concurrent.futures
import json
import re
import socket
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from pilosa_tpu import config as config_mod
from pilosa_tpu.cluster.topology import new_cluster
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import Executor, plan
from pilosa_tpu.exec.coalesce import CoalesceScheduler
from pilosa_tpu.net import handler as handler_mod
from pilosa_tpu.net.client import InternalClient
from pilosa_tpu.net.handler import Handler, Request
from pilosa_tpu.net.server import Server
from pilosa_tpu.obs import perf, prom
from pilosa_tpu.obs import stats as stats_mod
from pilosa_tpu.ops.bitplane import SLICE_WIDTH, WORDS_PER_SLICE
from pilosa_tpu.pql.parser import parse_string

ROW_SLOT_BYTES = WORDS_PER_SLICE * 4  # one plane row = 128 KiB

WAIT_US = 200_000


@pytest.fixture(autouse=True)
def _fresh_registry():
    """The perf registry is process-global (like the device pool) —
    isolate every test from its neighbors' launches."""
    perf.registry().reset()
    perf.registry().set_floor(0.0)
    perf.registry().configure(enabled=True)
    yield
    perf.registry().reset()
    perf.registry().set_floor(0.0)
    perf.registry().configure(enabled=True)


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def ex(holder):
    c = new_cluster(1)
    return Executor(holder, host=c.nodes[0].host, cluster=c)


def must_set_bits(holder, index, frame, bits, view="standard"):
    idx = holder.create_index_if_not_exists(index)
    f = idx.create_frame_if_not_exists(frame)
    for row, col in bits:
        f.set_bit(view, row, col)
    return f


def q(ex, index, pql):
    return ex.execute(index, parse_string(pql), None, None)


# ---------------------------------------------------------------------------
# registry units
# ---------------------------------------------------------------------------


class TestPerfRegistry:
    def test_plane_bytes_geometry(self):
        assert perf.plane_bytes(1, WORDS_PER_SLICE) == ROW_SLOT_BYTES
        assert perf.plane_bytes(3, 64) == 3 * 64 * 4

    def test_record_snapshot_gauges_and_floor_pct(self):
        r = perf.registry()
        r.set_floor(100.0)
        # 1 GB in 0.1 s of device time = 10 GB/s = 10% of the floor.
        r.record_launch(
            "coalesce", reduce="count", queries=4, rows=8,
            n_bytes=1_000_000_000, dispatch_ms=20.0, total_ms=100.0,
            trace_id="t1",
        )
        r.record_launch(
            "coalesce", reduce="row", queries=2, rows=2,
            n_bytes=0, total_ms=1.0, trace_id="t2",
        )
        snap = r.snapshot()
        site = snap["sites"]["coalesce"]
        assert site["launches"] == 2
        assert site["queries"] == 6
        assert site["occupancy"] == 3.0
        assert site["bytes"] == 1_000_000_000
        assert site["gbps"] == pytest.approx(1.0 / 0.101, rel=1e-3)
        assert site["floor_pct"] == pytest.approx(
            100.0 * site["gbps"] / 100.0, abs=0.11
        )
        assert site["reduces"] == {"count": 1, "row": 1}
        assert site["p99_ms"] > site["p50_ms"] > 0
        # Slowest table keeps the trace id for /debug/traces handoff.
        assert snap["slowest"][0]["trace_id"] == "t1"
        g = r.gauges()
        assert g["device.streamFloorGbps"] == 100.0
        assert g["exec.launch.launches[site:coalesce]"] == 2
        assert g["exec.launch.gbps[site:coalesce]"] == site["gbps"]
        assert g["exec.launch.floorPct[site:coalesce]"] == site["floor_pct"]

    def test_disabled_registry_records_nothing(self):
        r = perf.registry()
        r.configure(enabled=False)
        r.record_launch("direct", n_bytes=5, total_ms=1.0)
        assert r.snapshot()["sites"] == {}

    def test_module_shorthand_and_trace_id_outside_span(self):
        assert perf.current_trace_id() == ""
        perf.record_launch("topn", reduce="topn", total_ms=2.0)
        assert perf.registry().snapshot()["sites"]["topn"]["launches"] == 1


# ---------------------------------------------------------------------------
# native latency histograms + SLO burn
# ---------------------------------------------------------------------------


class TestLatencyHistograms:
    def test_cumulative_buckets_sum_count(self):
        lh = perf.LatencyHistograms(buckets_ms=[10.0, 100.0])
        for ms in (1.0, 5.0, 50.0, 500.0):
            lh.observe_query("point", ms)
        text = lh.render()
        assert "# TYPE pilosa_query_latency_ms histogram" in text
        assert 'pilosa_query_latency_ms_bucket{class="point",le="10"} 2' in text
        assert 'pilosa_query_latency_ms_bucket{class="point",le="100"} 3' in text
        assert 'pilosa_query_latency_ms_bucket{class="point",le="+Inf"} 4' in text
        assert 'pilosa_query_latency_ms_count{class="point"} 4' in text
        assert 'pilosa_query_latency_ms_sum{class="point"} 556' in text

    def test_http_family_keyed_by_route_template(self):
        lh = perf.LatencyHistograms()
        lh.observe_http("GET", "/index/{index}/query", 3.0)
        text = lh.render()
        assert (
            'pilosa_http_latency_ms_count{method="GET",'
            'path="/index/{index}/query"} 1'
        ) in text

    def test_slo_gauges_and_burn_rate(self):
        lh = perf.LatencyHistograms(
            buckets_ms=[10.0], slo_ms=10.0, slo_objective=0.9
        )
        for _ in range(8):
            lh.observe_query("heavy", 1.0)
        for _ in range(2):
            lh.observe_query("heavy", 100.0)  # 20% error, 10% budget
        text = lh.render()
        assert "pilosa_obs_slo_target_ms 10" in text
        assert "pilosa_obs_slo_objective 0.9" in text
        m = re.search(
            r'pilosa_obs_slo_error_rate\{class="heavy"\} ([0-9.]+)', text
        )
        assert m and float(m.group(1)) == pytest.approx(0.2)
        m = re.search(
            r'pilosa_obs_slo_burn_rate\{class="heavy"\} ([0-9.]+)', text
        )
        assert m and float(m.group(1)) == pytest.approx(2.0, rel=1e-3)

    def test_no_slo_no_slo_gauges(self):
        lh = perf.LatencyHistograms()
        lh.observe_query("point", 1.0)
        assert "slo" not in lh.render()

    def test_empty_render_is_empty(self):
        assert perf.LatencyHistograms().render() == ""


def test_route_template_normalization():
    assert (
        handler_mod._route_template(r"/index/(?P<index>[^/]+)/query")
        == "/index/{index}/query"
    )
    assert handler_mod._route_template(r"/metrics") == "/metrics"


# ---------------------------------------------------------------------------
# satellite 1: lifetime-monotonic histogram count/sum past the reservoir
# ---------------------------------------------------------------------------


class TestHistogramLifetimeTotals:
    def test_count_sum_monotonic_past_reservoir(self):
        c = stats_mod.ExpvarStatsClient()
        n = 5000  # > the 4096 reservoir
        for i in range(n):
            c.histogram("lat", float(i % 10))
        h = c.snapshot()["histograms"]["lat"]
        assert h["count"] == n
        assert h["sum"] == pytest.approx(sum(float(i % 10) for i in range(n)))
        # The windowed reservoir is still bounded.
        assert h["n"] <= 4096
        # One more observation: lifetime totals keep growing (the bug
        # this guards: reservoir-derived _count capped at 4096 breaks
        # Prometheus rate()).
        c.histogram("lat", 3.0)
        h2 = c.snapshot()["histograms"]["lat"]
        assert h2["count"] == n + 1
        assert h2["sum"] == pytest.approx(h["sum"] + 3.0)

    def test_prom_render_uses_lifetime_totals(self):
        c = stats_mod.ExpvarStatsClient()
        for i in range(4200):
            c.histogram("lat", 1.0)
        text = prom.render(c.snapshot())
        assert "pilosa_lat_count 4200" in text
        assert "pilosa_lat_sum 4200" in text

    def test_prom_render_legacy_snapshot_fallback(self):
        # A snapshot without lifetime totals (older producer) still
        # renders, deriving sum from the windowed mean.
        text = prom.render(
            {"histograms": {"lat": {"n": 4, "mean": 2.5, "min": 1.0,
                                    "max": 4.0, "p50": 2.5, "p90": 3.7,
                                    "p99": 3.97, "p999": 3.997}}}
        )
        assert "pilosa_lat_count 4" in text
        assert "pilosa_lat_sum 10" in text


# ---------------------------------------------------------------------------
# satellite 2: StatsD truncation at UTF-8 codepoint boundaries
# ---------------------------------------------------------------------------


class TestStatsDUtf8Truncation:
    def test_truncation_never_splits_a_codepoint(self):
        rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(2.0)
        port = rx.getsockname()[1]
        # 3-byte codepoints positioned so the 1432-byte cut lands
        # mid-rune for naive byte slicing.
        tags = [f"tag{i}:{'日本語' * 20}" for i in range(40)]
        c = stats_mod.StatsDClient(f"127.0.0.1:{port}").with_tags(*tags)
        try:
            c.count("bits", 1)
            data, _ = rx.recvfrom(65536)
            assert len(data) <= stats_mod.StatsDClient.MAX_PAYLOAD
            # The payload must decode — a mid-rune cut raises here.
            data.decode("utf-8")
            assert data.startswith(b"pilosa.bits:1|c")
        finally:
            rx.close()
            c.close()

    def test_cut_walks_back_over_continuation_bytes(self):
        # Unit-level: craft a payload whose MAX_PAYLOAD'th byte is a
        # continuation byte and check the boundary logic directly.
        base = "x" * (stats_mod.StatsDClient.MAX_PAYLOAD - 1) + "日"
        data = base.encode()
        cut = stats_mod.StatsDClient.MAX_PAYLOAD
        while cut > 0 and (data[cut] & 0xC0) == 0x80:
            cut -= 1
        assert data[:cut].decode("utf-8") == "x" * (
            stats_mod.StatsDClient.MAX_PAYLOAD - 1
        )


# ---------------------------------------------------------------------------
# satellite 3a: /metrics validity under a concurrent scrape-vs-writer storm
# ---------------------------------------------------------------------------

# Label VALUES may legally contain braces (e.g. the http route
# template path="/index/{index}/query"), so the label block is matched
# greedily to the last "}".
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? [-+0-9.einfa]+$"
)


def _assert_valid_exposition(text: str) -> None:
    assert text.endswith("\n")
    seen_types: dict[str, str] = {}
    seen_samples: set[str] = set()
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# TYPE "):
            _, _, fam, kind = line.split(" ")
            assert kind in ("counter", "gauge", "summary", "histogram"), line
            assert fam not in seen_types, f"duplicate # TYPE for {fam}"
            seen_types[fam] = kind
        else:
            assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
            key = line.rsplit(" ", 1)[0]
            assert key not in seen_samples, f"duplicate series: {key}"
            seen_samples.add(key)


class TestScrapeWriterStorm:
    def test_exposition_valid_under_concurrent_writes(self):
        c = stats_mod.ExpvarStatsClient()
        lh = perf.LatencyHistograms(slo_ms=5.0)
        stop = threading.Event()
        errs: list[BaseException] = []

        def writer(i: int):
            tagged = c.with_tags(f"index:i{i % 3}")
            j = 0
            try:
                while not stop.is_set():
                    tagged.count("storm.writes", 1)
                    tagged.histogram("storm.lat", float(j % 50))
                    c.gauge(f"storm.g{i}", float(j))
                    lh.observe_query(f"class{i % 2}", float(j % 20))
                    lh.observe_http("GET", "/metrics", 0.1)
                    perf.record_launch(
                        "coalesce", reduce="count", n_bytes=1024,
                        total_ms=0.01,
                    )
                    j += 1
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [
            threading.Thread(target=writer, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        try:
            deadline = time.monotonic() + 1.0
            scrapes = 0
            while time.monotonic() < deadline:
                text = prom.render(
                    c.snapshot(),
                    extra_gauges=perf.registry().gauges(),
                )
                text += lh.render()
                _assert_valid_exposition(text)
                scrapes += 1
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errs
        assert scrapes > 3
        # Final state: every writer family landed.
        final = prom.render(c.snapshot()) + lh.render()
        assert "pilosa_storm_writes_total" in final
        assert "pilosa_query_latency_ms_bucket" in final
        assert "pilosa_obs_slo_burn_rate" in final


# ---------------------------------------------------------------------------
# launch-site instrumentation through the coalescer
# ---------------------------------------------------------------------------


class TestCoalescerSites:
    def test_coalesce_and_interp_sites_record(self, rng):
        co = CoalesceScheduler(max_wait_us=WAIT_US)
        try:
            words = 64
            b = jnp.asarray(
                rng.integers(0, 2**32, size=(4, 2, words), dtype=np.uint32)
            )
            expr = ("Intersect", ("leaf", 0), ("leaf", 1))
            # Same program key twice -> one coalesced launch.
            futs = [co.submit(expr, "count", b) for _ in range(2)]
            for f in futs:
                f.result(timeout=30)
            # Distinct exprs -> fused interpreter launch.
            exprs = [
                ("Intersect", ("leaf", 0), ("leaf", 1)),
                ("Union", ("leaf", 0), ("leaf", 1)),
                ("Xor", ("leaf", 0), ("leaf", 1)),
            ]
            futs = [co.submit(e, "count", b) for e in exprs]
            for f in futs:
                f.result(timeout=30)
        finally:
            co.close()
        sites = perf.registry().snapshot()["sites"]
        assert sites["coalesce"]["launches"] >= 1
        assert sites["coalesce"]["queries"] >= 2
        # Logical bytes: pre-pad rows x words x 4.
        assert sites["coalesce"]["bytes"] % (words * 4) == 0
        assert sites["interp"]["launches"] >= 1
        assert sites["interp"]["queries"] >= 3
        assert sites["interp"]["device_ms"] > 0

    def test_total_reduce_site_records(self, rng):
        co = CoalesceScheduler(max_wait_us=0)
        try:
            b = jnp.asarray(
                rng.integers(0, 2**32, size=(2, 2, 64), dtype=np.uint32)
            )
            fut = co.submit(
                ("Intersect", ("leaf", 0), ("leaf", 1)), "total", b
            )
            fut.result(timeout=30)
        finally:
            co.close()
        sites = perf.registry().snapshot()["sites"]
        # Mesh present (virtual 8-device conftest) -> the ICI-reduced
        # collective site; single-device fallback -> "total".
        assert ("collective" in sites) or ("total" in sites)


# ---------------------------------------------------------------------------
# compile-time accounting
# ---------------------------------------------------------------------------


def test_program_cache_compile_ms_accumulates(rng):
    plan.clear_program_caches()
    co = CoalesceScheduler(max_wait_us=0)
    try:
        b = jnp.asarray(
            rng.integers(0, 2**32, size=(2, 2, 64), dtype=np.uint32)
        )
        co.submit(
            ("Intersect", ("leaf", 0), ("leaf", 1)), "count", b
        ).result(timeout=30)
    finally:
        co.close()
    ms = plan.program_cache_compile_ms()
    assert ms and all(v >= 0 for v in ms.values())
    plan.clear_program_caches()
    assert plan.program_cache_compile_ms() == {}


# ---------------------------------------------------------------------------
# single-node integration: the endpoints
# ---------------------------------------------------------------------------


@pytest.fixture
def perf_server(tmp_path):
    s = Server(
        data_dir=str(tmp_path / "data"),
        stats=stats_mod.ExpvarStatsClient(),
        slo_ms=50.0,
        coalesce_max_wait_us=WAIT_US,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
    )
    s.open()
    yield s
    s.close()


def _populate(s, rows=2, cols=(5, 9, SLICE_WIDTH + 3)):
    s.holder.create_index_if_not_exists("i")
    f = s.holder.index("i").create_frame_if_not_exists("f")
    for r in range(1, rows + 1):
        for col in cols:
            f.set_bit("standard", r, col + r)
    return f


class TestPerfEndpoint:
    def test_all_launch_sites_reported_with_floor_pct(self, perf_server):
        s = perf_server
        _populate(s)
        c = InternalClient(s.host, timeout=30.0)
        # topn site: the src bitmap forces the fused device scorer (a
        # bare TopN can answer straight from the ranked cache).
        c.execute_pql("i", "TopN(Bitmap(rowID=1, frame=f), frame=f, n=2)")
        # collective (mesh total-count) or total site.
        assert c.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == 3
        # coalesce site (row reduce through the scheduler).
        c.execute_pql("i", 'Bitmap(frame="f", rowID=1)')
        # interp site: a concurrent burst of DISTINCT row-reduce trees
        # sharing the dispatch window fuses into interpreter launches
        # (Count trees would take the collective path instead).
        pqls = [
            'Intersect(Bitmap(frame="f", rowID=1),'
            ' Bitmap(frame="f", rowID=2))',
            'Union(Bitmap(frame="f", rowID=1),'
            ' Bitmap(frame="f", rowID=2))',
            'Difference(Bitmap(frame="f", rowID=1),'
            ' Bitmap(frame="f", rowID=2))',
        ]
        with concurrent.futures.ThreadPoolExecutor(len(pqls)) as pool:
            list(pool.map(lambda p: c.execute_pql("i", p), pqls))
        # direct site: the uncoalesced executor path.
        co, s.executor.coalescer = s.executor.coalescer, None
        try:
            c.execute_pql("i", 'Bitmap(frame="f", rowID=2)')
        finally:
            s.executor.coalescer = co

        status, data, _ = c._request_meta("GET", "/debug/perf")
        assert status == 200
        doc = json.loads(data)
        assert doc["enabled"] is True
        # The open()-time stream-floor probe anchored the roofline.
        assert doc["floor_gbps"] > 0
        sites = doc["sites"]
        for site in ("direct", "coalesce", "interp", "topn"):
            assert site in sites, f"missing site {site}: {sorted(sites)}"
        assert ("collective" in sites) or ("total" in sites)
        for name, row in sites.items():
            assert row["launches"] >= 1, (name, row)
            assert row["gbps"] >= 0
            assert "floor_pct" in row, (name, row)
            assert row["dispatch_ms"] <= row["device_ms"] + 1e-6
        assert isinstance(doc["compile_ms"], dict)
        # Slowest launches carry trace ids for /debug/traces handoff.
        assert doc["slowest"]
        assert any(r["trace_id"] for r in doc["slowest"])

    def test_byte_accounting_matches_hbm_plane_geometry(self, perf_server):
        s = perf_server
        f = _populate(s, rows=1, cols=(1, 7))
        c = InternalClient(s.host, timeout=30.0)
        c.execute_pql("i", 'Bitmap(frame="f", rowID=1)')
        sites = perf.registry().snapshot()["sites"]
        launch = sites.get("coalesce") or sites.get("direct")
        assert launch is not None
        # Per-row bytes must equal the 128 KiB row-slot /debug/hbm
        # reports planes in — same words-per-slice geometry end to end.
        assert launch["rows"] >= 1
        assert launch["bytes"] == launch["rows"] * ROW_SLOT_BYTES
        status, data, _ = c._request_meta("GET", "/debug/hbm")
        assert status == 200
        hbm = json.loads(data)
        frag_rows = hbm.get("fragments", [])
        assert frag_rows, hbm
        # The resident device bytes for the launch's planes can only be
        # >= the logical (pre-pad) bytes perf accounted: device-side
        # padding and shard round-up add, never subtract.
        assert launch["bytes"] <= sum(r["bytes"] for r in frag_rows)

    def test_metrics_carries_perf_gauges_and_histograms(self, perf_server):
        s = perf_server
        _populate(s)
        c = InternalClient(s.host, timeout=30.0)
        assert c.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))') == 3
        status, data, _ = c._request_meta("GET", "/metrics")
        assert status == 200
        text = data.decode()
        _assert_valid_exposition(text)
        assert "pilosa_device_streamFloorGbps" in text
        assert re.search(r'pilosa_exec_launch_gbps\{site="', text), text
        assert re.search(r'pilosa_exec_launch_floorPct\{site="', text), text
        assert "# TYPE pilosa_query_latency_ms histogram" in text
        assert 'pilosa_query_latency_ms_bucket{class=' in text
        assert 'le="+Inf"' in text
        assert re.search(
            r'pilosa_http_latency_ms_count\{method="POST",'
            r'path="/index/\{index\}/query"\}', text
        ), text
        assert "pilosa_obs_slo_target_ms 50" in text
        assert "pilosa_obs_slo_burn_rate" in text

    def test_stacks_endpoint(self, perf_server):
        c = InternalClient(perf_server.host, timeout=30.0)
        status, data, headers = c._request_meta("GET", "/debug/stacks")
        assert status == 200
        assert headers["content-type"].startswith("text/plain")
        text = data.decode()
        assert "MainThread" in text
        assert "threads" in text.splitlines()[0]

    def test_profile_endpoint_end_to_end(self, perf_server, tmp_path):
        c = InternalClient(perf_server.host, timeout=60.0)
        status, data, _ = c._request_meta(
            "GET", "/debug/profile?seconds=0.05"
        )
        if status == 501:
            # Runtime without xprof support: the endpoint must say so,
            # not 500.  (CI containers have it; this guards minimal
            # installs.)
            return
        assert status == 200
        doc = json.loads(data)
        assert doc["seconds"] == pytest.approx(0.05)
        assert doc["trace"].endswith(".tar.gz")
        assert doc["bytes"] > 0
        # The tarball lands under the server's data dir.
        assert doc["trace"].startswith(perf_server.data_dir)

    def test_profile_501_when_profiler_missing(self, perf_server, monkeypatch):
        monkeypatch.setattr(handler_mod, "_jax_profiler", lambda: None)
        c = InternalClient(perf_server.host, timeout=30.0)
        status, data, _ = c._request_meta("GET", "/debug/profile?seconds=0.05")
        assert status == 501
        assert b"unavailable" in data

    def test_profile_bad_seconds_400(self, perf_server):
        c = InternalClient(perf_server.host, timeout=30.0)
        status, _, _ = c._request_meta("GET", "/debug/profile?seconds=junk")
        assert status == 400

    def test_profile_single_flight_409(self, perf_server):
        h = perf_server.handler
        assert h._profile_mu.acquire(blocking=False)
        try:
            c = InternalClient(perf_server.host, timeout=30.0)
            status, _, _ = c._request_meta(
                "GET", "/debug/profile?seconds=0.05"
            )
            assert status == 409
        finally:
            h._profile_mu.release()


# ---------------------------------------------------------------------------
# floor probe
# ---------------------------------------------------------------------------


class TestFloorProbe:
    def test_probe_measures_and_caches(self, tmp_path, monkeypatch):
        from pilosa_tpu.device import floorprobe

        floorprobe.reset_cache()
        calls = []
        real_measure = floorprobe._measure

        def counting_measure(*a, **kw):
            calls.append(1)
            return real_measure(*a, **kw)

        monkeypatch.setattr(floorprobe, "_measure", counting_measure)
        stats = stats_mod.ExpvarStatsClient()
        fp = floorprobe.probe(
            artifact_dir=str(tmp_path), stats=stats, logger=lambda m: None
        )
        assert fp is not None
        assert fp["mean_gbps"] > 0
        assert fp["gbps"]
        assert len(calls) == 1
        assert stats.snapshot()["gauges"]["device.streamFloorGbps"] == (
            pytest.approx(fp["mean_gbps"])
        )
        # Second probe: process cache, no re-measure.
        fp2 = floorprobe.probe(artifact_dir=str(tmp_path))
        assert fp2["mean_gbps"] == fp["mean_gbps"]
        assert len(calls) == 1
        # Fresh process (cache cleared): the disk artifact short-cuts.
        floorprobe.reset_cache()
        fp3 = floorprobe.probe(artifact_dir=str(tmp_path))
        assert fp3["mean_gbps"] == pytest.approx(fp["mean_gbps"])
        assert len(calls) == 1
        assert (tmp_path / floorprobe.CACHE_FILE).exists()
        # force=True re-measures.
        floorprobe.probe(artifact_dir=str(tmp_path), force=True)
        assert len(calls) == 2

    def test_server_open_sets_registry_floor(self, perf_server):
        assert perf.registry().floor_gbps() > 0

    def test_floor_probe_disabled(self, tmp_path):
        perf.registry().set_floor(0.0)
        s = Server(
            data_dir=str(tmp_path / "data2"),
            floor_probe=False,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
        )
        s.open()
        try:
            assert perf.registry().floor_gbps() == 0.0
        finally:
            s.close()


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


class TestObsConfig:
    def test_toml_roundtrip(self):
        cfg = config_mod.from_toml(
            "[obs]\n"
            "latency-buckets-ms = [5.0, 50.0, 500.0]\n"
            "slo-ms = 100.0\n"
            "slo-objective = 0.99\n"
            "floor-probe = false\n"
        )
        cfg.validate()
        assert cfg.obs.latency_buckets_ms == [5.0, 50.0, 500.0]
        assert cfg.obs.slo_ms == 100.0
        assert cfg.obs.slo_objective == 0.99
        assert cfg.obs.floor_probe is False
        cfg2 = config_mod.from_toml(cfg.to_toml())
        assert cfg2.obs.latency_buckets_ms == [5.0, 50.0, 500.0]
        assert cfg2.obs.floor_probe is False

    def test_env_overlay(self):
        cfg = config_mod.apply_env(
            config_mod.Config(),
            {
                "PILOSA_OBS_LATENCY_BUCKETS_MS": "1,10,100",
                "PILOSA_OBS_SLO_MS": "25",
                "PILOSA_OBS_SLO_OBJECTIVE": "0.95",
                "PILOSA_OBS_FLOOR_PROBE": "false",
            },
        )
        assert cfg.obs.latency_buckets_ms == [1.0, 10.0, 100.0]
        assert cfg.obs.slo_ms == 25.0
        assert cfg.obs.slo_objective == 0.95
        assert cfg.obs.floor_probe is False

    def test_validation_rejects_bad_values(self):
        cfg = config_mod.Config()
        cfg.obs.latency_buckets_ms = [10.0, 5.0]
        with pytest.raises(config_mod.ConfigError):
            cfg.validate()
        cfg = config_mod.Config()
        cfg.obs.latency_buckets_ms = [0.0, 5.0]
        with pytest.raises(config_mod.ConfigError):
            cfg.validate()
        cfg = config_mod.Config()
        cfg.obs.slo_objective = 1.0
        with pytest.raises(config_mod.ConfigError):
            cfg.validate()


# ---------------------------------------------------------------------------
# satellite 6: overhead guard — telemetry on vs off
# ---------------------------------------------------------------------------


class TestOverheadGuard:
    def test_telemetry_overhead_within_5pct(self, ex, holder):
        must_set_bits(
            holder, "i", "f",
            [(1, c) for c in range(0, 64, 3)]
            + [(1, SLICE_WIDTH + 7)],
        )
        # A row-reduce query on the uncoalesced path: the launch (and
        # its record_launch) runs ON the query thread, so the guard
        # measures the telemetry's true cost.  A collective Count would
        # run the record on the watchdog's worker thread, where GIL
        # handoff jitter between worker and waiting query thread
        # dwarfs — and randomly amplifies — the microseconds under
        # test.
        ex.coalescer = None
        call = parse_string('Bitmap(frame="f", rowID=1)')

        def batch(enabled: bool, n: int, sink: list) -> None:
            perf.registry().configure(enabled=enabled)
            for _ in range(n):
                t0 = time.perf_counter()
                ex.execute("i", call, None, None)
                sink.append(time.perf_counter() - t0)

        def p99(samples: list) -> float:
            samples = sorted(samples)
            return samples[int(len(samples) * 0.99)]

        # Warm compile caches and both code paths off the clock.
        batch(True, 50, [])
        batch(False, 50, [])
        # Fine-grained interleaving: alternate small on/off batches so
        # machine drift (GC, turbo, noisy CI neighbors) lands in both
        # pools equally, then compare the POOLED per-mode p99.  The GC
        # is parked during timing — collector pauses are the dominant
        # tail noise at this query size and have nothing to do with the
        # telemetry under test.
        import gc

        def measure() -> tuple[float, float]:
            # Per-round p99s, compared at the calmest round per mode:
            # the container shows occasional ~3 ms scheduler stalls
            # that poison a pooled p99, while a REAL overhead
            # regression shifts every round's tail including the best
            # one.
            on_p99s: list = []
            off_p99s: list = []
            gc.collect()
            gc.disable()
            try:
                for _ in range(6):
                    a: list = []
                    b: list = []
                    batch(True, 100, a)
                    batch(False, 100, b)
                    on_p99s.append(p99(a))
                    off_p99s.append(p99(b))
            finally:
                gc.enable()
            return min(on_p99s), min(off_p99s)

        # Up to three measurement passes: a single pass's p99 is one
        # sample of the scheduler-noise tail, so a real <=5% budget
        # needs a retry to not flake — a genuine overhead regression
        # fails every pass.
        results = []
        try:
            for _ in range(3):
                on, off = measure()
                results.append((on, off))
                if on <= off * 1.05 + 100e-6:
                    return
        finally:
            perf.registry().configure(enabled=True)
        pytest.fail(
            "telemetry overhead too high in all passes: "
            + ", ".join(
                f"on p99 {on*1e3:.3f} ms vs off p99 {off*1e3:.3f} ms"
                for on, off in results
            )
        )
