"""Admission control under storm (net/admission.py).

Covers the three contracts the ISSUE demands:

* a saturated class gate answers 429 + Retry-After BEFORE any
  coalescer/device work (asserted via the exec.coalesce.launches
  counter staying flat across a shed);
* remote map legs ride the internal priority lane and are never shed
  behind client traffic (livelock regression over 2 real HTTP nodes);
* a node that sheds even internal traffic degrades an ``allowPartial``
  query correctly — and never trips the coordinator's breaker.
"""

import json
import threading
import time
import urllib.request

import pytest

from pilosa_tpu import config as config_mod
from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.topology import Cluster
from pilosa_tpu.exec import plan
from pilosa_tpu.net import admission as adm
from pilosa_tpu.net import resilience as rz
from pilosa_tpu.net.client import InternalClient
from pilosa_tpu.net.server import Server
from pilosa_tpu.obs.stats import ExpvarStatsClient
from pilosa_tpu.pql.parser import parse_string


# ---------------------------------------------------------------------------
# cost classes
# ---------------------------------------------------------------------------


class TestCostClass:
    @pytest.mark.parametrize(
        "pql,want",
        [
            ('Count(Bitmap(frame="f", rowID=1))', plan.COST_POINT),
            ('Bitmap(frame="f", rowID=1)', plan.COST_POINT),
            (
                'Intersect(Bitmap(rowID=1), Union(Bitmap(rowID=2), Bitmap(rowID=3)))',
                plan.COST_POINT,
            ),
            ('TopN(frame="f", n=5)', plan.COST_HEAVY),
            ('Sum(frame="f", field="v")', plan.COST_HEAVY),
            ('Min(frame="f", field="v")', plan.COST_HEAVY),
            # Range nested anywhere makes the tree heavy.
            ('Count(Range(frame="f", v > 3))', plan.COST_HEAVY),
            (
                'Count(Intersect(Bitmap(rowID=1), Range(frame="f", v > 3)))',
                plan.COST_HEAVY,
            ),
            ('SetBit(frame="f", rowID=1, columnID=2)', plan.COST_WRITE),
            # write wins over heavy in a mixed batch
            (
                'SetBit(frame="f", rowID=1, columnID=2) TopN(frame="f", n=5)',
                plan.COST_WRITE,
            ),
        ],
    )
    def test_classification(self, pql, want):
        assert plan.cost_class(parse_string(pql).calls) == want


# ---------------------------------------------------------------------------
# gate unit behavior
# ---------------------------------------------------------------------------


class TestGate:
    def test_fast_path_admit_and_release(self):
        ac = adm.AdmissionController(point_concurrency=2, queue_depth=4)
        t1 = ac.acquire(adm.CLASS_POINT)
        t2 = ac.acquire(adm.CLASS_POINT)
        snap = ac.snapshot()[adm.CLASS_POINT]
        assert snap["active"] == 2 and snap["admitted"] == 2
        t1.release()
        t2.release()
        t2.release()  # idempotent
        assert ac.snapshot()[adm.CLASS_POINT]["active"] == 0

    def test_queue_full_sheds_with_retry_after(self):
        ac = adm.AdmissionController(point_concurrency=1, queue_depth=0)
        t = ac.acquire(adm.CLASS_POINT)
        with pytest.raises(rz.ShedError) as ei:
            ac.acquire(adm.CLASS_POINT)
        assert ei.value.retry_after_s > 0
        assert ei.value.cost_class == adm.CLASS_POINT
        assert ac.snapshot()[adm.CLASS_POINT]["shed"] == 1
        t.release()
        ac.acquire(adm.CLASS_POINT).release()

    def test_deadline_aware_shed_before_queueing(self):
        ac = adm.AdmissionController(point_concurrency=1, queue_depth=64)
        gate = ac.gate(adm.CLASS_POINT)
        gate._ewma_ms = 1000.0  # pretend service takes a second
        t = ac.acquire(adm.CLASS_POINT)
        # 50 ms of budget cannot cover a ~1 s predicted wait: shed NOW.
        with pytest.raises(rz.ShedError):
            ac.acquire(adm.CLASS_POINT, deadline=rz.Deadline.after_ms(50))
        t.release()

    def test_queue_wait_then_admit(self):
        ac = adm.AdmissionController(point_concurrency=1, queue_depth=4)
        t = ac.acquire(adm.CLASS_POINT)
        got = {}

        def waiter():
            tk = ac.acquire(adm.CLASS_POINT)
            got["wait_ms"] = tk.wait_ms
            tk.release()

        th = threading.Thread(target=waiter)
        th.start()
        deadline = time.time() + 2.0
        while time.time() < deadline:
            if ac.snapshot()[adm.CLASS_POINT]["queued"] == 1:
                break
            time.sleep(0.005)
        time.sleep(0.05)
        t.release()
        th.join(timeout=2.0)
        assert not th.is_alive()
        assert got["wait_ms"] >= 40.0

    def test_deadline_expiry_in_queue_sheds(self):
        ac = adm.AdmissionController(point_concurrency=1, queue_depth=4)
        t = ac.acquire(adm.CLASS_POINT)
        gate = ac.gate(adm.CLASS_POINT)
        gate._ewma_ms = 0.1  # prediction says the wait is tiny...
        t0 = time.monotonic()
        with pytest.raises(rz.ShedError):
            # ...but nobody releases: the waiter sheds at ITS deadline,
            # not after burning any work.
            ac.acquire(adm.CLASS_POINT, deadline=rz.Deadline.after_ms(80))
        assert time.monotonic() - t0 < 1.0
        assert ac.snapshot()[adm.CLASS_POINT]["queued"] == 0
        t.release()

    def test_ewma_feedback(self):
        ac = adm.AdmissionController(point_concurrency=1, queue_depth=0)
        gate = ac.gate(adm.CLASS_POINT)
        before = gate._ewma_ms
        t = ac.acquire(adm.CLASS_POINT)
        time.sleep(0.05)
        t.release()
        assert gate._ewma_ms != before  # observed service time folded in


# ---------------------------------------------------------------------------
# config knobs
# ---------------------------------------------------------------------------


class TestConfig:
    def test_toml_roundtrip(self):
        cfg = config_mod.from_toml(
            "[net]\n"
            "admission = false\n"
            "admission-point-concurrency = 3\n"
            "admission-heavy-concurrency = 2\n"
            "admission-write-concurrency = 4\n"
            "admission-internal-concurrency = 9\n"
            "admission-queue-depth = 7\n"
        )
        assert cfg.net.admission is False
        assert cfg.net.admission_point_concurrency == 3
        assert cfg.net.admission_internal_concurrency == 9
        assert "admission-queue-depth = 7" in cfg.to_toml()
        cfg.validate()

    def test_validation(self):
        cfg = config_mod.Config()
        cfg.net.admission_point_concurrency = 0
        with pytest.raises(config_mod.ConfigError):
            cfg.validate()

    def test_env_overlay(self):
        cfg = config_mod.apply_env(
            config_mod.Config(),
            environ={"PILOSA_NET_ADMISSION_QUEUE_DEPTH": "5"},
        )
        assert cfg.net.admission_queue_depth == 5


# ---------------------------------------------------------------------------
# single node over HTTP: shed before any device work
# ---------------------------------------------------------------------------


@pytest.fixture
def tight_server(tmp_path):
    """One-slot gates, zero queue: the second concurrent request of any
    client class MUST shed."""
    s = Server(
        data_dir=str(tmp_path / "data"),
        host="127.0.0.1:0",
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        stats=ExpvarStatsClient(),
        admission_point_concurrency=1,
        admission_heavy_concurrency=1,
        admission_write_concurrency=1,
        admission_queue_depth=0,
    )
    s.open()
    s.holder.create_index_if_not_exists("i")
    s.holder.index("i").create_frame_if_not_exists("f")
    s.holder.frame("i", "f").set_bit("standard", 1, 10)
    yield s
    s.close()


def _raw_query(host: str, pql: str, headers: dict | None = None):
    """(status, headers, parsed-json-body) without the client's
    ShedError translation — tests assert the raw HTTP contract."""
    req = urllib.request.Request(
        f"http://{host}/index/i/query", data=pql.encode(), method="POST",
        headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


class TestServerShedding:
    def _counts(self, server) -> dict:
        return server.stats.snapshot()["counts"]

    def test_saturated_sheds_429_before_coalescer(self, tight_server):
        s = tight_server
        q = 'Count(Bitmap(frame="f", rowID=1))'
        # Warm once so the coalescer counter is live.
        status, _, body = _raw_query(s.host, q)
        assert status == 200 and body["results"] == [1]
        launches_before = self._counts(s).get("exec.coalesce.launches", 0)

        ticket = s.admission.acquire(adm.CLASS_POINT)
        try:
            status, headers, body = _raw_query(s.host, q)
        finally:
            ticket.release()
        assert status == 429
        assert int(headers["Retry-After"]) >= 1
        assert body["retryAfterMs"] > 0
        assert "admission" in body["error"]
        # The shed happened BEFORE the executor/coalescer: no launch.
        counts = self._counts(s)
        assert counts.get("exec.coalesce.launches", 0) == launches_before
        assert counts.get("net.admission.shed[class:point]", 0) == 1

        # Slot free again: the same query succeeds.
        status, _, body = _raw_query(s.host, q)
        assert status == 200 and body["results"] == [1]

    def test_classes_gate_independently(self, tight_server):
        s = tight_server
        ticket = s.admission.acquire(adm.CLASS_POINT)
        try:
            # point saturated; heavy still admits
            status, _, _ = _raw_query(s.host, 'TopN(frame="f", n=2)')
            assert status == 200
        finally:
            ticket.release()

    def test_import_value_sheds_write_class(self, tight_server):
        s = tight_server
        payload = json.dumps(
            {
                "index": "i", "frame": "f", "field": "x",
                "slice": 0, "columnIDs": [1], "values": [2],
            }
        ).encode()
        ticket = s.admission.acquire(adm.CLASS_WRITE)
        try:
            req = urllib.request.Request(
                f"http://{s.host}/import-value", data=payload, method="POST"
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 429
            assert "Retry-After" in dict(ei.value.headers)
        finally:
            ticket.release()

    def test_health_and_metrics_surface_queue_state(self, tight_server):
        s = tight_server
        with urllib.request.urlopen(
            f"http://{s.host}/debug/health", timeout=10
        ) as resp:
            health = json.loads(resp.read())
        assert set(health["admission"]) == set(adm.CLASSES)
        assert health["admission"]["point"]["concurrency"] == 1
        with urllib.request.urlopen(
            f"http://{s.host}/metrics", timeout=10
        ) as resp:
            metrics = resp.read().decode()
        assert 'net_admission_active{class="point"}' in metrics
        assert 'net_admission_queued{class="heavy"}' in metrics

    def test_admission_span_in_trace(self, tight_server):
        s = tight_server
        _raw_query(s.host, 'Count(Bitmap(frame="f", rowID=1))')
        names = {
            sp["name"]
            for tr in s.tracer.traces()
            for sp in tr["spans"]
        }
        assert "admission" in names

    def test_shed_does_not_trip_breaker(self, tight_server):
        """A healthy-but-busy host answering 429 must stay breaker-
        closed on the caller side, even with a hair-trigger breaker."""
        s = tight_server
        breakers = rz.BreakerRegistry(failure_threshold=1)
        client = InternalClient(s.host, timeout=10.0, breakers=breakers)
        ticket = s.admission.acquire(adm.CLASS_POINT)
        try:
            for _ in range(3):
                with pytest.raises(rz.ShedError) as ei:
                    client.execute_query(
                        "i", 'Count(Bitmap(frame="f", rowID=1))'
                    )
                assert ei.value.retry_after_s > 0
            assert breakers.state(s.host) == rz.STATE_CLOSED
        finally:
            ticket.release()
        # And the host still serves: shed never poisoned anything.
        assert client.execute_pql(
            "i", 'Count(Bitmap(frame="f", rowID=1))'
        ) == 1


# ---------------------------------------------------------------------------
# two real HTTP nodes: internal priority + degraded reads
# ---------------------------------------------------------------------------


@pytest.fixture
def two_tight_servers(tmp_path):
    recv0, recv1 = bc.HTTPBroadcastReceiver(), bc.HTTPBroadcastReceiver()
    b0, b1 = bc.HTTPBroadcaster([]), bc.HTTPBroadcaster([])
    servers = []
    for i, (recv, b) in enumerate(((recv0, b0), (recv1, b1))):
        s = Server(
            data_dir=str(tmp_path / f"n{i}"),
            cluster=Cluster(replica_n=1),
            broadcaster=b,
            broadcast_receiver=recv,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
            stats=ExpvarStatsClient(),
            retry_backoff_ms=10,
            admission_point_concurrency=1,
            admission_heavy_concurrency=1,
            admission_write_concurrency=1,
            admission_queue_depth=0,
            admission_internal_concurrency=2,
        )
        s.open()
        servers.append(s)
    s0, s1 = servers
    b0.internal_hosts.append(recv1.bound_host)
    b1.internal_hosts.append(recv0.bound_host)
    for s in servers:
        for host in sorted([s0.host, s1.host]):
            if s.cluster.node_by_host(host) is None:
                s.cluster.add_node(host)
        s.cluster.nodes.sort(key=lambda n: n.host)
    yield s0, s1
    s0.close()
    s1.close()


def _seed_distributed(s0, s1, n_slices=6):
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    for s in (s0, s1):
        s.holder.create_index_if_not_exists("i")
        s.holder.index("i").create_frame_if_not_exists("f")
    for sl in range(n_slices):
        owner = s0.cluster.fragment_nodes("i", sl)[0].host
        srv = s0 if owner == s0.host else s1
        srv.holder.frame("i", "f").set_bit("standard", 1, sl * SLICE_WIDTH)
    for s in (s0, s1):
        s.holder.index("i").set_remote_max_slice(n_slices - 1)
    # sanity: both nodes own something
    owned1 = [
        sl for sl in range(n_slices)
        if s0.cluster.fragment_nodes("i", sl)[0].host == s1.host
    ]
    assert owned1, "placement gave node 1 nothing; widen n_slices"
    return n_slices, owned1


class TestInternalPriority:
    def test_map_legs_never_shed_behind_client_traffic(
        self, two_tight_servers
    ):
        """Livelock regression: every CLIENT gate on the remote node is
        saturated, yet a coordinator fan-out still answers — remote map
        legs ride the internal lane."""
        s0, s1 = two_tight_servers
        n_slices, _ = _seed_distributed(s0, s1)
        tickets = [
            s1.admission.acquire(cls)
            for cls in (adm.CLASS_POINT, adm.CLASS_HEAVY, adm.CLASS_WRITE)
        ]
        try:
            c0 = InternalClient(s0.host, timeout=15.0)
            got = c0.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))')
            assert got == n_slices
        finally:
            for t in tickets:
                t.release()
        # the remote legs really did admit through the internal lane
        counts = s1.stats.snapshot()["counts"]
        assert counts.get("net.admission.admitted[class:internal]", 0) >= 1

    def test_internal_shed_degrades_allow_partial(self, two_tight_servers):
        """A node saturated PAST its internal lane sheds map legs; the
        coordinator treats that as a node failure: allowPartial reduces
        over the survivors, and the shed never trips s1's breaker."""
        s0, s1 = two_tight_servers
        n_slices, owned1 = _seed_distributed(s0, s1)
        # Saturate the internal lane for an immediate shed (no queue).
        s1.admission.gate(adm.CLASS_INTERNAL).queue_depth = 0
        tickets = [
            s1.admission.acquire(adm.CLASS_INTERNAL) for _ in range(2)
        ]
        try:
            status, headers, body = _raw_query(
                s0.host,
                'Count(Bitmap(frame="f", rowID=1))',
                headers={"X-Allow-Partial": "true"},
            )
            assert status == 200
            assert body["partial"] is True
            assert sorted(body["missingSlices"]) == sorted(owned1)
            assert body["results"] == [n_slices - len(owned1)]
            # shedding is not a breaker event on the coordinator
            assert (
                s0.resilience.breakers.state(s1.host) == rz.STATE_CLOSED
            )
        finally:
            for t in tickets:
                t.release()
        # Lane free again: the same query is whole.
        status, _, body = _raw_query(
            s0.host, 'Count(Bitmap(frame="f", rowID=1))'
        )
        assert status == 200 and body["results"] == [n_slices]
