"""Compiled-program cardinality under schema churn (ROADMAP 2a).

The serving contract: compiled-program count is O(1) in schema shape.
Every jit compile key is canonicalized — plane rows, candidate slots,
fragment-group sizes, and batch slice axes all bucket to powers of two
— so a churny schema (many frames, each with a different row count)
reuses a handful of compiled programs instead of minting one per
fragment shape at ~326 ms of XLA compile each.

The regression tests below create >= 32 DISTINCT fragment-set /
plane-set shapes, run the standard query mix over every one on both
the direct and the coalesced executor paths, and assert via the
``exec.programCache.*`` gauges (plan.program_cache_stats) that each
jit family stays <= 4 compiled programs — with results byte-identical
to an unpadded host (numpy) evaluation.
"""

from __future__ import annotations

import numpy as np
import pytest

from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import plan
from pilosa_tpu.exec.coalesce import CoalesceScheduler
from pilosa_tpu.exec.executor import Executor
from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.pql.parser import parse_string

N_FRAMES = 32

BOUNDED_FAMILIES = (
    "plan.batched",
    "plan.totalCount",
    "bitplane.scorePlanes",
    "bitplane.topCounts",
)


def _frame_name(k: int) -> str:
    return f"f{k:02d}"


@pytest.fixture
def churny(tmp_path, rng):
    """One index, N_FRAMES frames; frame k holds a single slice-0
    fragment with k+1 rows — 32 distinct raw fragment shapes (and,
    after pow2 padding, exactly the {8, 16, 32} plane classes)."""
    holder = Holder(str(tmp_path))
    holder.open()
    idx = holder.create_index("i")
    bits: dict[str, dict[int, list[int]]] = {}
    for k in range(N_FRAMES):
        f = idx.create_frame(_frame_name(k), cache_size=64)
        view = f.create_view_if_not_exists("standard")
        frag = view.create_fragment_if_not_exists(0)
        rows = k + 1
        per_row: dict[int, list[int]] = {}
        for r in range(rows):
            cols = sorted(
                int(c)
                for c in np.unique(
                    rng.integers(0, bp.SLICE_WIDTH, size=r + 3)
                )
            )
            for c in cols:
                frag.set_bit(r, c)
            per_row[r] = cols
        bits[_frame_name(k)] = per_row
    yield holder, bits
    holder.close()


def _expected_count_and(per_row, r1: int, r2: int) -> int:
    return len(set(per_row[r1]) & set(per_row[r2]))


def _expected_topn(per_row, src_row: int, n: int):
    """Unpadded host reference: |row AND src| per row, (-count, id)."""
    src = set(per_row[src_row])
    scored = [
        (r, len(set(cols) & src)) for r, cols in per_row.items()
    ]
    scored = [(r, c) for r, c in scored if c > 0]
    scored.sort(key=lambda p: (-p[1], p[0]))
    return scored[:n] if n else scored


def _run_mix(ex, bits):
    """The standard mix over every churny frame: a 2-leaf
    Intersect+Count and a same-frame TopN(src).  Returns
    [(got_count, want_count, got_pairs, want_pairs)] per frame."""
    out = []
    for name, per_row in bits.items():
        rows = len(per_row)
        r2 = rows - 1
        q = parse_string(
            f"Count(Intersect(Bitmap(rowID=0, frame={name}),"
            f" Bitmap(rowID={r2}, frame={name})))"
        )
        (got_count,) = ex.execute("i", q)
        tq = parse_string(
            f"TopN(Bitmap(rowID=0, frame={name}), frame={name}, n={rows})"
        )
        (got_pairs,) = ex.execute("i", tq)
        out.append(
            (
                int(got_count),
                _expected_count_and(per_row, 0, r2),
                [(p.id, p.count) for p in got_pairs],
                _expected_topn(per_row, 0, rows),
            )
        )
    return out


def _assert_mix(results):
    for got_count, want_count, got_pairs, want_pairs in results:
        assert got_count == want_count
        assert got_pairs == want_pairs


def _assert_bounded(limit: int = 4):
    stats = plan.program_cache_stats()
    bounds = plan.program_cache_bounds()
    for fam in BOUNDED_FAMILIES:
        assert stats[fam] <= limit, (fam, stats)
        assert stats[fam] <= bounds[fam], (fam, stats, bounds)


class TestChurnySchemaCardinality:
    def test_direct_path(self, churny):
        holder, bits = churny
        plan.clear_program_caches()
        ex = Executor(holder)
        try:
            _assert_mix(_run_mix(ex, bits))
        finally:
            ex.close()
        # >= 32 distinct fragment shapes -> <= 4 programs per family.
        _assert_bounded()
        stats = plan.program_cache_stats()
        assert stats["bitplane.scorePlanes"] >= 1  # the scorer DID run

    def test_coalesced_path(self, churny):
        holder, bits = churny
        plan.clear_program_caches()
        co = CoalesceScheduler()
        ex = Executor(holder, coalescer=co)
        try:
            _assert_mix(_run_mix(ex, bits))
        finally:
            ex.close()
            co.close()
        _assert_bounded()

    def test_direct_and_coalesced_agree(self, churny):
        """Byte-identical results whichever path compiled the programs."""
        holder, bits = churny
        plan.clear_program_caches()
        ex1 = Executor(holder)
        co = CoalesceScheduler()
        ex2 = Executor(holder, coalescer=co)
        try:
            direct = _run_mix(ex1, bits)
            coalesced = _run_mix(ex2, bits)
        finally:
            ex1.close()
            ex2.close()
            co.close()
        for d, c in zip(direct, coalesced):
            assert d[0] == c[0] and d[2] == c[2]
        _assert_bounded()


class TestBucketHelpers:
    def test_pad_rows_pow2_classes(self):
        # 1..32 raw row counts land in exactly 3 shape classes.
        classes = {bp.pad_rows(r) for r in range(1, 33)}
        assert classes == {8, 16, 32}
        assert bp.pad_rows(0) == bp.ROW_BLOCK
        assert bp.pad_rows(33) == 64

    def test_bucket_classes(self):
        assert bp.bucket_classes(8, 8) == 1
        assert bp.bucket_classes(32, 8) == 3
        assert bp.bucket_classes(256, 8) == 6
        assert bp.bucket_classes(1) == 1
        assert bp.bucket_classes(4) == 3  # {1, 2, 4}

    def test_slice_bucket(self):
        assert [plan.slice_bucket(n) for n in (1, 2, 3, 5, 9)] == [
            1,
            2,
            4,
            8,
            16,
        ]

    def test_wider_churn_stays_under_bucket_count(self, tmp_path, rng):
        """Row counts spanning 8..256 (32 distinct multiples of 8 — the
        shapes that each minted a program under the old multiple-of-8
        padding) stay within the pow2 bucket-class bound."""
        plan.clear_program_caches()
        holder = Holder(str(tmp_path))
        holder.open()
        idx = holder.create_index("i")
        ex = Executor(holder)
        try:
            for k in range(1, 33):
                name = f"w{k:02d}"
                f = idx.create_frame(name, cache_size=512)
                view = f.create_view_if_not_exists("standard")
                frag = view.create_fragment_if_not_exists(0)
                rows = 8 * k  # 8, 16, ..., 256
                for r in range(rows):
                    frag.set_bit(r, (r * 37) % bp.SLICE_WIDTH)
                    frag.set_bit(r, (r * 91 + 7) % bp.SLICE_WIDTH)
                tq = parse_string(
                    f"TopN(Bitmap(rowID=0, frame={name}), frame={name}, n=4)"
                )
                ex.execute("i", tq)
            stats = plan.program_cache_stats()
            bounds = plan.program_cache_bounds()
            # The satellite bar: each family <= its bucket count.  The
            # slot/row grids over [8, 256] have 6 pow2 classes; the old
            # multiple-of-8 padding produced up to 32 per family here.
            assert stats["bitplane.scorePlanes"] <= bounds[
                "bitplane.scorePlanes"
            ]
            assert stats["bitplane.scorePlanes"] <= 2 * bp.bucket_classes(
                256, bp.ROW_BLOCK
            ) ** 2
            assert stats["bitplane.topCounts"] <= bounds["bitplane.topCounts"]
        finally:
            ex.close()
            holder.close()


def test_program_cache_bounds_invariant_after_prewarm():
    """entries <= bound must hold after the standard prewarm too — the
    invariant the /metrics bound gauges advertise."""
    from pilosa_tpu.exec import warmup

    plan.clear_program_caches()
    warmup.prewarm(buckets=(1, 2), exprs=warmup._STANDARD_EXPRS[:2])
    stats = plan.program_cache_stats()
    bounds = plan.program_cache_bounds()
    for fam, bound in bounds.items():
        assert stats[fam] <= bound, (fam, stats, bounds)
    assert stats["total"] > 0
