"""PQL parser tests (parity tier for pql/*_test.go)."""

import pytest

from pilosa_tpu import pql


def parse1(s):
    q = pql.parse_string(s)
    assert len(q.calls) == 1
    return q.calls[0]


def test_basic_call():
    c = parse1('Bitmap(rowID=1, frame="f")')
    assert c.name == "Bitmap"
    assert c.args == {"rowID": 1, "frame": "f"}
    assert c.children == []


def test_nested_children_and_args():
    c = parse1('TopN(Bitmap(rowID=1, frame="other"), frame="f", n=20)')
    assert c.name == "TopN"
    assert [ch.name for ch in c.children] == ["Bitmap"]
    assert c.args == {"frame": "f", "n": 20}


def test_multi_call_query():
    q = pql.parse_string("SetBit(id=1, frame='f', col=2)\nCount(Bitmap(id=1))")
    assert [c.name for c in q.calls] == ["SetBit", "Count"]
    assert q.write_call_n() == 1


def test_value_types():
    c = parse1(
        'F(a=true, b=false, c=null, d=ident, e="str", f=42, g=-1, h=1.5, '
        "i=[1,2,3], j=['x', y, true])"
    )
    assert c.args["a"] is True
    assert c.args["b"] is False
    assert c.args["c"] is None
    assert c.args["d"] == "ident"
    assert c.args["e"] == "str"
    assert c.args["f"] == 42
    assert c.args["g"] == -1
    assert c.args["h"] == 1.5
    assert c.args["i"] == [1, 2, 3]
    assert c.args["j"] == ["x", "y", True]


def test_string_escapes():
    c = parse1('F(a="x\\ny", b="q\\"w", c=\'it\\\'s\')')
    assert c.args["a"] == "x\ny"
    assert c.args["b"] == 'q"w'
    assert c.args["c"] == "it's"


def test_canonical_string_sorted_keys():
    c = parse1('SetBit(id=1, frame="f", col=10)')
    assert str(c) == 'SetBit(col=10, frame="f", id=1)'


def test_canonical_string_children_first():
    c = parse1('Count(Union(Bitmap(a=1), Bitmap(a=2)), x="y")')
    assert str(c) == 'Count(Union(Bitmap(a=1), Bitmap(a=2)), x="y")'


def test_canonical_string_values():
    c = parse1("F(a=true, b=null, c=1.5, d=2.0, e=[1,2], f=[\"s\", t])")
    # null (not Go's "<nil>") so the canonical string re-parses for
    # remote forwarding.
    assert str(c) == 'F(a=true, b=null, c=1.5, d=2, e=[1,2], f=["s","t"])'


def test_roundtrip_canonical():
    src = 'TopN(Bitmap(frame="o", rowID=5), field="q", filters=["a",2], frame="f", n=10)'
    assert str(parse1(src)) == src


def test_uint_arg():
    c = parse1("F(a=5, b=-1, s=\"x\")")
    assert c.uint_arg("a") == 5
    assert c.uint_arg("missing") is None
    assert c.uint_arg("b") == 2 ** 64 - 1  # negative wraps like Go's cast
    with pytest.raises(TypeError):
        c.uint_arg("s")


def test_uint_slice_arg():
    c = parse1("F(ids=[1,2,3], bad=[1,\"x\"])")
    assert c.uint_slice_arg("ids") == [1, 2, 3]
    assert c.uint_slice_arg("missing") is None
    with pytest.raises(TypeError):
        c.uint_slice_arg("bad")


def test_is_inverse():
    assert parse1("Bitmap(columnID=1)").is_inverse("rowID", "columnID")
    assert not parse1("Bitmap(rowID=1)").is_inverse("rowID", "columnID")
    assert not parse1("Bitmap(rowID=1, columnID=2)").is_inverse("rowID", "columnID")
    assert parse1("TopN(inverse=true)").is_inverse("rowID", "columnID")
    assert not parse1("TopN(inverse=false)").is_inverse("rowID", "columnID")
    assert not parse1("Union(columnID=1)").is_inverse("rowID", "columnID")


def test_clone_independent():
    c = parse1('Count(Bitmap(rowID=1), x="y")')
    c2 = c.clone()
    c2.args["x"] = "z"
    c2.children[0].args["rowID"] = 9
    assert c.args["x"] == "y"
    assert c.children[0].args["rowID"] == 1


@pytest.mark.parametrize("bad", [
    "",
    "Bitmap(",
    "Bitmap)",
    "Bitmap(rowID=)",
    "Bitmap(rowID=1",
    "Bitmap(rowID=1 frame=2)",
    "Bitmap(rowID=1, rowID=2)",
    "5(x=1)",
    'F(a="unterminated)',
    'F(a="bad\\escape")',
    "F(a=[1,)",
    "F(a=1,,b=2)",
])
def test_parse_errors(bad):
    with pytest.raises(pql.ParseError):
        pql.parse_string(bad)


def test_ident_chars():
    c = parse1("Range(frame=my-frame.v2_x, start=1)")
    assert c.args["frame"] == "my-frame.v2_x"


# ---------------------------------------------------------------------------
# BSI comparison arguments (Range(field > 100), Sum/Min/Max)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("op", ["<", "<=", "==", "!=", ">=", ">"])
def test_comparison_ops(op):
    c = parse1(f"Range(frame=f, v {op} 100)")
    assert c.name == "Range"
    conds = c.conditions()
    assert set(conds) == {"v"}
    assert conds["v"].op == op
    assert conds["v"].value == 100
    assert c.args["frame"] == "f"


@pytest.mark.parametrize("value", [-1, -1000, -(2**40)])
def test_comparison_negative_values(value):
    c = parse1(f"Range(frame=f, v >= {value})")
    assert c.conditions()["v"].value == value


def test_between_two_int_list():
    c = parse1("Range(frame=f, v >< [-10, 42])")
    cond = c.conditions()["v"]
    assert cond.op == "><"
    assert cond.value == [-10, 42]


def test_comparison_longest_first_lexing():
    # ">=" must not lex as ">" "="; "><" must not lex as ">" "<".
    assert parse1("F(a >= 1)").conditions()["a"].op == ">="
    assert parse1("F(a >< [1, 2])").conditions()["a"].op == "><"


@pytest.mark.parametrize(
    "q",
    [
        "Range(frame=f, v > 100)",
        "Range(frame=f, v <= -5)",
        "Range(frame=f, v != 0)",
        "Range(frame=f, v >< [-10, 42])",
        'Count(Intersect(Range(frame=f, v > 0), Bitmap(frame="f", rowID=1)))',
        'Sum(Range(frame=f, v < 0), field="v", frame="f")',
    ],
)
def test_comparison_roundtrip(q):
    """Canonical str() of a comparison call re-parses to an equal tree —
    the property remote query forwarding depends on."""
    c1 = parse1(q)
    c2 = parse1(str(c1))
    assert str(c1) == str(c2)
    assert c2.conditions() == c1.conditions() or not c1.conditions()


def test_comparison_mixed_with_eq_args():
    c = parse1("Range(frame=f, v > 3)")
    # ordinary args and comparison args coexist; only Cond values are
    # conditions
    assert c.args["frame"] == "f"
    assert list(c.conditions()) == ["v"]


def test_comparison_duplicate_key_rejected():
    with pytest.raises(pql.ParseError):
        pql.parse_string("Range(frame=f, v > 1, v < 5)")


def test_comparison_missing_value_rejected():
    with pytest.raises(pql.ParseError):
        pql.parse_string("Range(frame=f, v >)")
