"""Multi-tenant QoS (net/admission.py tenant layer).

Covers the PR's tenant contracts end to end:

* weighted-fair queueing inside a class gate: deficit rotation serves
  a hot tenant ``weight`` grants per round, so a victim tenant's first
  request lands within one rotation of the hot tenant's backlog — and
  a single tenant degenerates to the exact legacy FIFO;
* per-tenant quotas: token-bucket accounting, refill over time, and
  the 429 + ``X-Quota-Limit`` / ``X-Quota-Remaining`` / ``Retry-After``
  HTTP contract on both the JSON and protobuf paths — while OTHER
  tenants keep admitting;
* the internal lane is quota-exempt but token-gated: a client cannot
  spoof the protobuf ``Remote`` flag past tenant QoS;
* remote map legs charge the ORIGINATING tenant on every node they
  touch (2 real HTTP nodes, forwarded ``X-Tenant``).
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.topology import Cluster
from pilosa_tpu.net import admission as adm
from pilosa_tpu.net import resilience as rz
from pilosa_tpu.net import wire_pb2 as wire
from pilosa_tpu.net.server import Server
from pilosa_tpu.obs.stats import ExpvarStatsClient

# ---------------------------------------------------------------------------
# spec parsing + resolution
# ---------------------------------------------------------------------------


class TestTenantSpec:
    def test_parse_full(self):
        t = adm.Tenant.parse("gold:8:100:1e6")
        assert (t.name, t.weight, t.qps, t.bytes_per_s) == ("gold", 8, 100.0, 1e6)

    def test_parse_defaults(self):
        t = adm.Tenant.parse("bronze")
        assert (t.weight, t.qps, t.bytes_per_s) == (1, 0.0, 0.0)

    @pytest.mark.parametrize("bad", ["", ":3", "x:lots", "x:1:fast"])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            adm.Tenant.parse(bad)


class TestResolution:
    def _reg(self):
        return adm.TenantRegistry(
            tenants=["gold:4", "bronze:1"],
            keys=["sekret:gold"],
            internal_token="tok",
        )

    def test_api_key_wins_over_header(self):
        reg = self._reg()
        assert reg.resolve("sekret", "bronze") == "gold"

    def test_bare_header_only_for_configured_tenants(self):
        reg = self._reg()
        assert reg.resolve("", "bronze") == "bronze"
        # arbitrary client-chosen names must NOT mint tenants
        assert reg.resolve("", "made-up") == adm.DEFAULT_TENANT

    def test_unknown_key_falls_to_default(self):
        reg = self._reg()
        assert reg.resolve("wrong", "") == adm.DEFAULT_TENANT

    def test_internal_token_gate(self):
        reg = self._reg()
        assert reg.internal_ok("tok")
        assert not reg.internal_ok("")
        assert not reg.internal_ok("guess")
        # no token configured: lane is open (pre-tenant deployments)
        assert adm.TenantRegistry().internal_ok("")


# ---------------------------------------------------------------------------
# weighted-fair queueing
# ---------------------------------------------------------------------------


def _drain_in_order(ac, arrivals):
    """Enqueue ``arrivals`` — (tenant, tag) pairs — one at a time
    behind a held slot (concurrency=1), release the slot, and return
    the tags in grant order.  Serial releases make the DRR schedule
    the only ordering force."""
    blocker = ac.acquire(adm.CLASS_POINT, tenant="blocker")
    gate = ac.gate(adm.CLASS_POINT)
    order, olock = [], threading.Lock()
    threads = []

    def waiter(tenant, tag):
        tk = ac.acquire(adm.CLASS_POINT, tenant=tenant)
        with olock:
            order.append(tag)
        tk.release()

    for tenant, tag in arrivals:
        before = gate.snapshot()["queued"]
        th = threading.Thread(target=waiter, args=(tenant, tag))
        th.start()
        threads.append(th)
        deadline = 200
        while gate.snapshot()["queued"] != before + 1 and deadline:
            threading.Event().wait(0.01)
            deadline -= 1
        assert deadline, "waiter never queued"
    blocker.release()
    for th in threads:
        th.join(timeout=10)
        assert not th.is_alive()
    return order


class TestWeightedFairQueue:
    def _controller(self, tenants):
        reg = adm.TenantRegistry(tenants=tenants)
        return adm.AdmissionController(
            point_concurrency=1, queue_depth=64, tenants=reg
        )

    def test_deficit_rotation_serves_weight_per_round(self):
        """hot(weight 3) vs victim(weight 1): each rotation grants ~3
        hot then 1 victim — victims appear every ≤4 grants, not after
        the hot backlog drains."""
        ac = self._controller(["hot:3", "victim:1"])
        arrivals = [("hot", f"h{i}") for i in range(12)]
        arrivals += [("victim", f"v{i}") for i in range(4)]
        order = _drain_in_order(ac, arrivals)
        assert len(order) == 16
        v_positions = [i for i, tag in enumerate(order) if tag[0] == "v"]
        # i-th victim grant within (i+1) rotations of (3 hot + 1 victim)
        for i, pos in enumerate(v_positions):
            assert pos <= (i + 1) * 4, f"victim {i} starved: order={order}"

    def test_starvation_bound_one_rotation(self):
        """A victim's FIRST request waits at most ~one rotation (hot's
        weight grants), no matter how deep hot's backlog is."""
        ac = self._controller(["hot:8", "victim:1"])
        arrivals = [("hot", f"h{i}") for i in range(24)]
        arrivals += [("victim", "v0")]
        order = _drain_in_order(ac, arrivals)
        assert order.index("v0") <= 9, f"victim starved: order={order}"

    def test_single_tenant_degenerates_to_fifo(self):
        ac = self._controller(["solo:1"])
        arrivals = [("solo", f"s{i}") for i in range(6)]
        order = _drain_in_order(ac, arrivals)
        assert order == [f"s{i}" for i in range(6)]

    def test_fifo_within_one_tenant_under_contention(self):
        """DRR must preserve arrival order INSIDE each tenant."""
        ac = self._controller(["hot:2", "cold:1"])
        arrivals = [("hot", "h0"), ("cold", "c0"), ("hot", "h1"),
                    ("cold", "c1"), ("hot", "h2")]
        order = _drain_in_order(ac, arrivals)
        assert [t for t in order if t[0] == "h"] == ["h0", "h1", "h2"]
        assert [t for t in order if t[0] == "c"] == ["c0", "c1"]


# ---------------------------------------------------------------------------
# quotas: accounting + refill
# ---------------------------------------------------------------------------


class TestQuotaAccounting:
    def test_qps_bucket_debits_then_sheds(self):
        reg = adm.TenantRegistry(tenants=["metered:1:3"])
        for _ in range(3):
            reg.check_quota("metered", adm.CLASS_POINT)
        with pytest.raises(adm.QuotaError) as ei:
            reg.check_quota("metered", adm.CLASS_POINT)
        e = ei.value
        assert e.status == 429
        assert e.tenant == "metered"
        assert e.quota_kind == "qps"
        assert e.quota_limit == 3.0
        assert e.quota_remaining < 1.0
        assert e.retry_after_s > 0

    def test_bucket_refills_over_time(self):
        reg = adm.TenantRegistry(tenants=["metered:1:2"])
        reg.check_quota("metered", adm.CLASS_POINT)
        reg.check_quota("metered", adm.CLASS_POINT)
        with pytest.raises(adm.QuotaError):
            reg.check_quota("metered", adm.CLASS_POINT)
        # rewind the bucket clock one second: full refill, admits again
        st = reg._state["metered"]
        st.qps_bucket.t_last -= 1.0
        reg.check_quota("metered", adm.CLASS_POINT)

    def test_bytes_quota_charges_ingress(self):
        reg = adm.TenantRegistry(tenants=["bulk:1:0:100"])
        reg.check_quota("bulk", adm.CLASS_WRITE, nbytes=60)
        with pytest.raises(adm.QuotaError) as ei:
            reg.check_quota("bulk", adm.CLASS_WRITE, nbytes=60)
        assert ei.value.quota_kind == "bytes"
        assert ei.value.quota_limit == 100.0

    def test_unmetered_tenant_never_sheds(self):
        reg = adm.TenantRegistry(tenants=["free:1"])
        for _ in range(100):
            reg.check_quota("free", adm.CLASS_POINT)

    def test_internal_lane_is_quota_exempt(self):
        """The controller skips quota for CLASS_INTERNAL: map legs were
        paid for at the coordinator's front door."""
        reg = adm.TenantRegistry(tenants=["metered:1:1"])
        ac = adm.AdmissionController(tenants=reg)
        for _ in range(5):
            ac.acquire(adm.CLASS_INTERNAL, tenant="metered").release()
        # client class still meters
        ac.acquire(adm.CLASS_POINT, tenant="metered").release()
        with pytest.raises(adm.QuotaError):
            ac.acquire(adm.CLASS_POINT, tenant="metered")

    def test_quota_shed_counts_in_snapshot(self):
        reg = adm.TenantRegistry(tenants=["metered:1:1"])
        ac = adm.AdmissionController(tenants=reg)
        ac.acquire(adm.CLASS_POINT, tenant="metered").release()
        with pytest.raises(adm.QuotaError):
            ac.acquire(adm.CLASS_POINT, tenant="metered")
        snap = ac.tenants_snapshot()["metered"]
        assert snap["quotaShed"] == 1
        assert snap["shed"] == 1
        assert snap["admitted"] == 1
        assert snap["quota"]["qps"]["limit"] == 1.0


# ---------------------------------------------------------------------------
# HTTP contract: one node
# ---------------------------------------------------------------------------


@pytest.fixture
def tenant_server(tmp_path):
    """Tenants: hot is API-keyed with a 3 qps quota; victim and the
    default tenant are unmetered."""
    s = Server(
        data_dir=str(tmp_path / "data"),
        host="127.0.0.1:0",
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        stats=ExpvarStatsClient(),
        tenants=["hot:8:3", "victim:1"],
        tenant_keys=["sekret:hot"],
        tenant_internal_token="tok",
    )
    s.open()
    s.holder.create_index_if_not_exists("i")
    s.holder.index("i").create_frame_if_not_exists("f")
    s.holder.frame("i", "f").set_bit("standard", 1, 10)
    yield s
    s.close()


def _raw(host, path, data=b"", headers=None, method="POST"):
    """(status, headers, raw body) — no client-side translation."""
    req = urllib.request.Request(
        f"http://{host}{path}", data=data, method=method,
        headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


_Q = b'Count(Bitmap(frame="f", rowID=1))'


def _storm_until_429(host, headers, n=6):
    """Fire up to ``n`` queries; return the first 429 triple."""
    for _ in range(n):
        status, hdrs, body = _raw(host, "/index/i/query", _Q, headers)
        if status == 429:
            return status, hdrs, body
        assert status == 200, body
    raise AssertionError("quota never tripped")


class TestQuotaHTTPContract:
    def test_json_429_with_quota_headers(self, tenant_server):
        s = tenant_server
        status, hdrs, body = _storm_until_429(
            s.host, {"X-Api-Key": "sekret"}
        )
        assert status == 429
        assert hdrs["X-Quota-Limit"] == "3"
        assert float(hdrs["X-Quota-Remaining"]) < 1.0
        assert int(hdrs["Retry-After"]) >= 1
        parsed = json.loads(body)
        assert parsed["quota"]["tenant"] == "hot"
        assert parsed["quota"]["kind"] == "qps"
        assert parsed["quota"]["limit"] == 3.0
        assert parsed["retryAfterMs"] > 0

    def test_protobuf_429_with_quota_headers(self, tenant_server):
        s = tenant_server
        status, hdrs, body = _storm_until_429(
            s.host,
            {"X-Api-Key": "sekret", "Accept": "application/x-protobuf"},
        )
        assert status == 429
        assert hdrs["X-Quota-Limit"] == "3"
        assert "X-Quota-Remaining" in hdrs
        resp = wire.QueryResponse()
        resp.ParseFromString(body)
        assert "quota" in resp.Err

    def test_other_tenants_admit_while_hot_sheds(self, tenant_server):
        """The acceptance-criteria shape: saturate hot's quota, then
        victim and the default tenant both still answer 200."""
        s = tenant_server
        _storm_until_429(s.host, {"X-Api-Key": "sekret"})
        status, _, _ = _raw(s.host, "/index/i/query", _Q,
                            {"X-Tenant": "victim"})
        assert status == 200
        status, _, _ = _raw(s.host, "/index/i/query", _Q)
        assert status == 200
        # and hot is STILL shedding (bucket not magically reset)
        status, _, _ = _raw(s.host, "/index/i/query", _Q,
                            {"X-Api-Key": "sekret"})
        assert status == 429

    def test_debug_tenants_table(self, tenant_server):
        s = tenant_server
        _storm_until_429(s.host, {"X-Api-Key": "sekret"})
        _raw(s.host, "/index/i/query", _Q, {"X-Tenant": "victim"})
        status, _, body = _raw(s.host, "/debug/tenants", method="GET")
        assert status == 200
        table = json.loads(body)
        assert table["defaultTenant"] == "default"
        hot = table["tenants"]["hot"]
        assert hot["quotaShed"] >= 1
        assert hot["admitted"] >= 1
        assert hot["quota"]["qps"]["limit"] == 3.0
        assert table["tenants"]["victim"]["admitted"] >= 1
        assert table["tenants"]["victim"]["quotaShed"] == 0

    def test_per_tenant_counters_emitted(self, tenant_server):
        s = tenant_server
        _storm_until_429(s.host, {"X-Api-Key": "sekret"})
        counts = s.stats.snapshot()["counts"]
        # ExpvarStatsClient renders tags sorted
        assert counts.get("net.admission.tenantAdmitted[class:point,tenant:hot]", 0) >= 1
        assert counts.get("net.admission.quotaShed[kind:qps,tenant:hot]", 0) >= 1
        # the executor also labels its class counter with the tenant
        assert counts.get("exec.class[class:point,tenant:hot]", 0) >= 1


class TestInternalLaneSpoofing:
    def _pb_query(self, host, token=""):
        pb = wire.QueryRequest(Query=_Q.decode(), Remote=True)
        headers = {
            "Content-Type": "application/x-protobuf",
            "Accept": "application/x-protobuf",
        }
        if token:
            headers["X-Internal-Token"] = token
        return _raw(host, "/index/i/query", pb.SerializeToString(), headers)

    def test_spoofed_remote_flag_charged_as_client(self, tenant_server):
        """Remote=true WITHOUT the internal token: classified and
        metered as ordinary client traffic."""
        s = tenant_server
        before = s.stats.snapshot()["counts"]
        status, _, _ = self._pb_query(s.host)
        assert status == 200
        after = s.stats.snapshot()["counts"]
        key_int = "net.admission.admitted[class:internal]"
        key_pt = "net.admission.admitted[class:point]"
        assert after.get(key_int, 0) == before.get(key_int, 0)
        assert after.get(key_pt, 0) == before.get(key_pt, 0) + 1

    def test_token_holder_rides_internal_lane(self, tenant_server):
        s = tenant_server
        before = s.stats.snapshot()["counts"]
        status, _, _ = self._pb_query(s.host, token="tok")
        assert status == 200
        after = s.stats.snapshot()["counts"]
        key_int = "net.admission.admitted[class:internal]"
        assert after.get(key_int, 0) == before.get(key_int, 0) + 1


# ---------------------------------------------------------------------------
# two real HTTP nodes: remote legs charge the originating tenant
# ---------------------------------------------------------------------------


@pytest.fixture
def two_tenant_servers(tmp_path):
    """Tenant 'gold' is configured (with its API key) on the
    COORDINATOR only — the remote node must still charge 'gold' via
    the forwarded X-Tenant on the verified internal lane."""
    recv0, recv1 = bc.HTTPBroadcastReceiver(), bc.HTTPBroadcastReceiver()
    b0, b1 = bc.HTTPBroadcaster([]), bc.HTTPBroadcaster([])
    servers = []
    for i, (recv, b) in enumerate(((recv0, b0), (recv1, b1))):
        s = Server(
            data_dir=str(tmp_path / f"n{i}"),
            cluster=Cluster(replica_n=1),
            broadcaster=b,
            broadcast_receiver=recv,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
            stats=ExpvarStatsClient(),
            retry_backoff_ms=10,
            tenants=["gold:4"] if i == 0 else [],
            tenant_keys=["goldkey:gold"] if i == 0 else [],
            tenant_internal_token="fleet-tok",
        )
        s.open()
        servers.append(s)
    s0, s1 = servers
    b0.internal_hosts.append(recv1.bound_host)
    b1.internal_hosts.append(recv0.bound_host)
    for s in servers:
        for host in sorted([s0.host, s1.host]):
            if s.cluster.node_by_host(host) is None:
                s.cluster.add_node(host)
        s.cluster.nodes.sort(key=lambda n: n.host)
    yield s0, s1
    s0.close()
    s1.close()


def _seed_distributed(s0, s1, n_slices=6):
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    for s in (s0, s1):
        s.holder.create_index_if_not_exists("i")
        s.holder.index("i").create_frame_if_not_exists("f")
    for sl in range(n_slices):
        owner = s0.cluster.fragment_nodes("i", sl)[0].host
        srv = s0 if owner == s0.host else s1
        srv.holder.frame("i", "f").set_bit("standard", 1, sl * SLICE_WIDTH)
    for s in (s0, s1):
        s.holder.index("i").set_remote_max_slice(n_slices - 1)
    owned1 = [
        sl for sl in range(n_slices)
        if s0.cluster.fragment_nodes("i", sl)[0].host == s1.host
    ]
    assert owned1, "placement gave node 1 nothing; widen n_slices"
    return n_slices, owned1


class TestRemoteLegCharging:
    def test_fanout_charged_to_origin_tenant_on_remote_node(
        self, two_tenant_servers
    ):
        s0, s1 = two_tenant_servers
        n_slices, _ = _seed_distributed(s0, s1)
        status, _, body = _raw(
            s0.host, "/index/i/query", _Q, {"X-Api-Key": "goldkey"}
        )
        assert status == 200
        assert json.loads(body)["results"] == [n_slices]
        # Coordinator charged gold on the client lane...
        snap0 = s0.admission.tenants_snapshot()
        assert snap0["gold"]["admitted"] >= 1
        assert "point" in snap0["gold"]["classes"]
        # ...and the REMOTE node charged the forwarded tenant on the
        # internal lane — auto-created, since s1 never configured gold.
        snap1 = s1.admission.tenants_snapshot()
        assert snap1["gold"]["admitted"] >= 1
        assert snap1["gold"]["classes"]["internal"]["admitted"] >= 1

    def test_untagged_fanout_charges_default(self, two_tenant_servers):
        s0, s1 = two_tenant_servers
        n_slices, _ = _seed_distributed(s0, s1)
        status, _, body = _raw(s0.host, "/index/i/query", _Q)
        assert status == 200
        assert json.loads(body)["results"] == [n_slices]
        snap1 = s1.admission.tenants_snapshot()
        assert snap1["default"]["classes"]["internal"]["admitted"] >= 1
