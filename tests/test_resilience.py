"""Cluster resilience chaos suite: deadlines, retries, breakers, faults.

Unit coverage of net/resilience.py and testing/faults.py, then
end-to-end chaos over real two-node HTTP clusters: breakers open under
injected transport errors and recover through a half-open probe;
expired deadlines answer 504 (coordinator and remote leg) carrying the
trace id; ``allowPartial`` queries return results byte-identical to a
fault-free run restricted to the surviving slices with ``missingSlices``
listing exactly the lost ones; retries respect their caps; and a
deadline-expired coalesce waiter detaches without poisoning the shared
batch.
"""

import json
import socket
import threading
import time
from concurrent.futures import Future
from contextlib import suppress
from types import SimpleNamespace

import numpy as np
import pytest

from pilosa_tpu.cluster.topology import Cluster
from pilosa_tpu.exec.executor import Executor
from pilosa_tpu.net import resilience as rz
from pilosa_tpu.net.client import InternalClient
from pilosa_tpu.net.server import Server
from pilosa_tpu.ops.bitplane import SLICE_WIDTH
from pilosa_tpu.testing import faults


@pytest.fixture(autouse=True)
def _no_faults():
    """Every test starts and ends fault-free (the plan is process
    global)."""
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


class TestDeadline:
    def test_remaining_and_expiry(self):
        dl = rz.Deadline.after_ms(10_000)
        assert 9.0 < dl.remaining() <= 10.0
        assert not dl.expired
        assert rz.Deadline.after_ms(0).expired

    def test_clamp_bounds_timeout_by_budget(self):
        dl = rz.Deadline.after_ms(1_000)
        assert dl.clamp(30.0) <= 1.0
        assert dl.clamp(0.1) == pytest.approx(0.1, abs=0.01)
        assert rz.Deadline.after_ms(0).clamp(30.0) == 0.0

    def test_header_roundtrip(self):
        dl = rz.Deadline.after_ms(5_000)
        back = rz.Deadline.from_header(dl.header_value())
        assert 4.0 < back.remaining() <= 5.0
        assert rz.Deadline.from_header("") is None
        assert rz.Deadline.from_header("not-a-number") is None
        # An about-to-expire deadline still travels as >= 1 ms.
        assert int(rz.Deadline.after_ms(0.01).header_value()) >= 1

    def test_scope_and_check(self):
        assert rz.current_deadline() is None
        rz.check_deadline()  # no deadline -> no-op
        with rz.deadline_scope(rz.Deadline.after_ms(10_000)):
            assert rz.current_deadline() is not None
            rz.check_deadline()
        assert rz.current_deadline() is None
        with rz.deadline_scope(rz.Deadline.after_ms(0)):
            with pytest.raises(rz.DeadlineExceeded):
                rz.check_deadline("unit")

    def test_scope_crosses_threads_via_contextvars(self):
        """The executor pool copies contextvars into workers — the
        mechanism deadline propagation rides."""
        import contextvars

        seen = []
        with rz.deadline_scope(rz.Deadline.after_ms(10_000)):
            ctx = contextvars.copy_context()
        t = threading.Thread(
            target=lambda: seen.append(ctx.run(rz.current_deadline))
        )
        t.start()
        t.join()
        assert seen[0] is not None and not seen[0].expired


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_transient_failure_retried_to_success(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return 7

        policy = rz.RetryPolicy(attempts=3, backoff=0.001)
        assert policy.call(flaky) == 7
        assert len(calls) == 3

    def test_attempt_cap_respected(self):
        calls = []

        def dead():
            calls.append(1)
            raise OSError("down")

        policy = rz.RetryPolicy(attempts=3, backoff=0.001)
        with pytest.raises(OSError):
            policy.call(dead)
        assert len(calls) == 3

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("semantic")

        policy = rz.RetryPolicy(attempts=5, backoff=0.001)
        with pytest.raises(ValueError):
            policy.call(bad)
        assert len(calls) == 1
        # BreakerOpen and DeadlineExceeded are never retried either.
        for exc in (rz.BreakerOpenError("h:1"), rz.DeadlineExceeded()):
            calls.clear()

            def gated(exc=exc):
                calls.append(1)
                raise exc

            with pytest.raises(type(exc)):
                policy.call(gated)
            assert len(calls) == 1

    def test_expired_deadline_stops_retries_as_504_shape(self):
        policy = rz.RetryPolicy(attempts=5, backoff=0.001)
        calls = []

        def dead():
            calls.append(1)
            raise OSError("down")

        with rz.deadline_scope(rz.Deadline.after_ms(0)):
            with pytest.raises(rz.DeadlineExceeded):
                policy.call(dead)
        assert len(calls) == 1

    def test_sleep_never_exceeds_budget(self):
        policy = rz.RetryPolicy(attempts=2, backoff=5.0, jitter=0.0)
        t0 = time.monotonic()
        with rz.deadline_scope(rz.Deadline.after_ms(100)):
            with pytest.raises((OSError, rz.DeadlineExceeded)):
                policy.call(lambda: (_ for _ in ()).throw(OSError("x")))
        # A 5 s base backoff must have been clamped to the ~0.1 s budget.
        assert time.monotonic() - t0 < 1.0

    def test_shed_retried_honoring_retry_after(self):
        """A 429 shed retries like a transport failure, but never
        sooner than the server's Retry-After hint."""
        calls = []

        def busy():
            calls.append(time.monotonic())
            if len(calls) < 3:
                raise rz.ShedError("busy", retry_after_s=0.05)
            return "ok"

        policy = rz.RetryPolicy(attempts=3, backoff=0.001, jitter=0.0)
        t0 = time.monotonic()
        assert policy.call(
            busy, retryable=rz.TRANSPORT_ERRORS + (rz.ShedError,)
        ) == "ok"
        assert len(calls) == 3
        # Two waits, each at least the 50 ms hint.
        assert time.monotonic() - t0 >= 0.09

    def test_shed_beyond_budget_propagates_for_failover(self):
        """Retry-After longer than the remaining deadline: propagate
        the ShedError immediately (the caller fails over to a replica)
        instead of sleeping into a guaranteed 504."""
        calls = []

        def busy():
            calls.append(1)
            raise rz.ShedError("busy", retry_after_s=10.0)

        policy = rz.RetryPolicy(attempts=5, backoff=0.001, jitter=0.0)
        t0 = time.monotonic()
        with rz.deadline_scope(rz.Deadline.after_ms(200)):
            with pytest.raises(rz.ShedError):
                policy.call(
                    busy, retryable=rz.TRANSPORT_ERRORS + (rz.ShedError,)
                )
        assert len(calls) == 1
        assert time.monotonic() - t0 < 1.0

    def test_shed_is_node_failure_but_not_5xx(self):
        e = rz.ShedError("busy", retry_after_s=0.5)
        assert rz.is_node_failure(e)  # eligible for replica failover
        assert e.status == 429
        assert e.retry_after_s == 0.5


# ---------------------------------------------------------------------------
# circuit breakers
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_opens_after_consecutive_failures(self):
        b = rz.CircuitBreaker("h:1", failure_threshold=3, open_s=60)
        for _ in range(2):
            b.record_failure()
        assert b.state == rz.STATE_CLOSED and b.allow()
        b.record_failure()
        assert b.state == rz.STATE_OPEN
        assert not b.allow()

    def test_success_resets_consecutive_count(self):
        b = rz.CircuitBreaker("h:1", failure_threshold=2, open_s=60)
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state == rz.STATE_CLOSED

    def test_half_open_probe_then_close(self):
        b = rz.CircuitBreaker("h:1", failure_threshold=1, open_s=0.05)
        b.record_failure()
        assert b.state == rz.STATE_OPEN and not b.allow()
        time.sleep(0.06)
        assert b.allow()  # the half-open probe
        assert b.state == rz.STATE_HALF_OPEN
        assert not b.allow()  # one probe at a time
        b.record_success()
        assert b.state == rz.STATE_CLOSED and b.allow()

    def test_half_open_probe_failure_reopens(self):
        b = rz.CircuitBreaker("h:1", failure_threshold=1, open_s=0.05)
        b.record_failure()
        time.sleep(0.06)
        assert b.allow()
        b.record_failure()
        assert b.state == rz.STATE_OPEN
        assert not b.allow()
        assert b.opens == 2

    def test_stale_probe_expires_instead_of_wedging(self):
        b = rz.CircuitBreaker("h:1", failure_threshold=1, open_s=0.05)
        b.record_failure()
        time.sleep(0.06)
        assert b.allow()  # probe taken... and its caller vanishes
        time.sleep(0.06)
        assert b.allow()  # a fresh probe is admitted

    def test_registry_check_and_snapshot(self):
        reg = rz.BreakerRegistry(failure_threshold=2, open_s=60)
        reg.check("a:1")  # closed -> admitted
        reg.record("a:1", False)
        reg.record("a:1", False)
        with pytest.raises(rz.BreakerOpenError):
            reg.check("a:1")
        snap = reg.snapshot()
        assert snap["a:1"]["state"] == rz.STATE_OPEN
        assert snap["a:1"]["opens"] == 1
        assert reg.state("missing:1") == rz.STATE_CLOSED


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------


class TestFaults:
    def test_parse_spec(self):
        plan = faults.parse(
            "rpc.send:host=h:1,path=/index/*/query,nth=2,mode=error;"
            "rpc.recv:prob=0.5,seed=42,mode=delay,delay-ms=15,times=3"
        )
        r0, r1 = plan.rules
        assert (r0.stage, r0.host, r0.path, r0.nth, r0.mode) == (
            "rpc.send", "h:1", "/index/*/query", 2, "error",
        )
        assert (r1.stage, r1.prob, r1.delay_ms, r1.times) == (
            "rpc.recv", 0.5, 15.0, 3,
        )

    def test_parse_rejects_garbage(self):
        for bad in ("noseparator", "rpc.send:frobnicate=1",
                    "rpc.send:mode=implode", "rpc.send:nth"):
            with pytest.raises(faults.FaultSpecError):
                faults.parse(bad)

    def test_nth_fires_exactly_once(self):
        plan = faults.install("rpc.send:nth=2,mode=error")
        plan.check("rpc.send")  # call 1: no fire
        with pytest.raises(faults.FaultError):
            plan.check("rpc.send")  # call 2: fires
        plan.check("rpc.send")  # call 3: no fire
        assert plan.rules[0].hits == 1 and plan.rules[0].calls == 3

    def test_times_caps_total_fires(self):
        plan = faults.install("rpc.send:times=2,mode=error")
        for _ in range(2):
            with pytest.raises(faults.FaultError):
                plan.check("rpc.send")
        plan.check("rpc.send")
        assert plan.rules[0].hits == 2

    def test_window_opens_and_closes(self):
        """after-ms/until-ms bound a rule to a timeline window measured
        from plan install — the gameday's composed-failure clock."""
        plan = faults.install(
            "rpc.send:mode=error,after-ms=40,until-ms=120"
        )
        plan.check("rpc.send")  # t≈0: window not open yet
        time.sleep(0.06)
        with pytest.raises(faults.FaultError):
            plan.check("rpc.send")  # inside [40, 120)
        time.sleep(0.09)
        plan.check("rpc.send")  # window closed again
        assert plan.rules[0].hits == 1
        # outside-window calls don't advance nth/times accounting
        assert plan.rules[0].calls == 1

    def test_window_rearm_resets_epoch(self):
        plan = faults.install("rpc.send:mode=error,after-ms=40")
        time.sleep(0.05)
        with pytest.raises(faults.FaultError):
            plan.check("rpc.send")
        plan.rearm()
        plan.check("rpc.send")  # epoch reset: window closed again
        snap = plan.snapshot()[0]
        assert snap["afterMs"] == 40.0 and "untilMs" not in snap

    def test_window_rejects_inverted_bounds(self):
        with pytest.raises(faults.FaultSpecError):
            faults.parse("rpc.send:after-ms=200,until-ms=100")

    def test_host_and_path_filters(self):
        plan = faults.install(
            "rpc.send:host=a:1,path=/index/*/query,mode=error"
        )
        plan.check("rpc.send", host="b:2", path="/index/i/query")
        plan.check("rpc.send", host="a:1", path="/schema")
        plan.check("rpc.recv", host="a:1", path="/index/i/query")
        assert plan.rules[0].hits == 0
        with pytest.raises(faults.FaultError):
            plan.check("rpc.send", host="a:1", path="/index/i/query")

    def test_prob_is_seed_deterministic(self):
        def decisions(seed):
            plan = faults.parse(f"device.launch:prob=0.5,seed={seed}")
            out = []
            for _ in range(32):
                try:
                    plan.check("device.launch")
                    out.append(False)
                except faults.FaultError:
                    out.append(True)
            return out

        a, b = decisions(7), decisions(7)
        assert a == b
        assert any(a) and not all(a)
        assert decisions(8) != a

    def test_delay_mode_sleeps_then_continues(self):
        plan = faults.install("rpc.recv:mode=delay,delay-ms=30")
        t0 = time.monotonic()
        plan.check("rpc.recv")
        assert time.monotonic() - t0 >= 0.025

    def test_drop_mode_raises_socket_timeout(self):
        plan = faults.install("rpc.send:mode=drop")
        with pytest.raises(socket.timeout):
            plan.check("rpc.send")

    def test_clear_disables_and_module_check_routes(self):
        faults.install("rpc.send:mode=error")
        with pytest.raises(faults.FaultError):
            faults.check("rpc.send")
        faults.clear()
        faults.check("rpc.send")  # no-op


# ---------------------------------------------------------------------------
# coalesce waiter regression: deadline expiry detaches, never poisons
# ---------------------------------------------------------------------------


class _StubCoalescer:
    """A coalescer whose launch never completes until the test says so —
    the shared-batch stand-in for a slow fused program."""

    def __init__(self):
        self.fut = Future()
        self.submits = 0

    def submit(self, expr, reduce, batch, pin_keys=(), leaf_keys=None):
        self.submits += 1
        return self.fut


class TestCoalesceWaiterDeadline:
    def _executor(self):
        ex = Executor(
            holder=SimpleNamespace(stats=None),
            host="h:1",
            cluster=Cluster(),
        )
        ex.coalescer = _StubCoalescer()
        return ex

    def test_expired_waiter_detaches_without_poisoning_shared_batch(self):
        ex = self._executor()
        stub = ex.coalescer
        ent = {
            "batch": np.zeros((2, 1, 8), dtype=np.uint32),
            "expr": ("leaf", 0),
            "pos_of": {0: 0, 1: 1},
            "pool_key": None,
        }
        t0 = time.monotonic()
        with rz.deadline_scope(rz.Deadline.after_ms(60)):
            with pytest.raises(rz.DeadlineExceeded):
                ex._coalesce_eval(ent, "count")
        assert time.monotonic() - t0 < 5.0  # not the flat 600 s wait
        # The shared launch was NOT cancelled by the departing waiter...
        assert not stub.fut.cancelled()
        # ...so a surviving waiter of the same launch still gets rows.
        stub.fut.set_result(
            (np.array([3, 4], dtype=np.int32), {"batch_queries": 2})
        )
        res = ex._coalesce_eval(ent, "count")
        assert list(res) == [3, 4]
        assert stub.submits == 2
        ex.close()

    def test_flat_backstop_timeout_preserved_without_deadline(self, monkeypatch):
        """No deadline -> the RESULT_TIMEOUT_S backstop still applies
        (shrunk here) and surfaces as the original TimeoutError."""
        from concurrent.futures import TimeoutError as FuturesTimeoutError

        from pilosa_tpu.exec import coalesce as coalesce_mod

        ex = self._executor()
        monkeypatch.setattr(coalesce_mod, "RESULT_TIMEOUT_S", 0.05)
        ent = {
            "batch": np.zeros((1, 1, 8), dtype=np.uint32),
            "expr": ("leaf", 0),
            "pos_of": {0: 0},
            "pool_key": None,
        }
        with pytest.raises(FuturesTimeoutError):
            ex._coalesce_eval(ent, "count")
        ex.close()


# ---------------------------------------------------------------------------
# end-to-end chaos over two real HTTP nodes
# ---------------------------------------------------------------------------

_QUIET = dict(
    anti_entropy_interval=3600,
    polling_interval=3600,
    cache_flush_interval=3600,
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _two_servers(tmp_path, replicas=1, **server_kw):
    """Two fixed-port nodes sharing a static cluster map (no broadcast
    machinery — remote max slices are set explicitly by the tests)."""
    kw = dict(_QUIET)
    kw.update(server_kw)
    ports: set[int] = set()
    while len(ports) < 2:
        ports.add(_free_port())
    hosts = sorted(f"127.0.0.1:{p}" for p in ports)

    def make(name, host):
        cluster = Cluster(replica_n=replicas)
        s = Server(
            data_dir=str(tmp_path / name), host=host, cluster=cluster, **kw
        )
        s.open()
        for h in hosts:
            if cluster.node_by_host(h) is None:
                cluster.add_node(h)
        cluster.nodes.sort(key=lambda n: n.host)
        return s

    s0, s1 = make("n0", hosts[0]), make("n1", hosts[1])
    for s in (s0, s1):
        s.holder.create_index_if_not_exists("i")
        s.holder.index("i").create_frame_if_not_exists("f")
    return s0, s1


def _seed_slices(s0, s1, n_slices=6, row=1):
    """One bit per slice, written straight into the owning holder, and
    both nodes told the cluster max slice (no broadcast wait)."""
    for sl in range(n_slices):
        owner = s0.cluster.fragment_nodes("i", sl)[0].host
        srv = s0 if owner == s0.host else s1
        srv.holder.frame("i", "f").set_bit("standard", row, sl * SLICE_WIDTH)
    for s in (s0, s1):
        s.holder.index("i").set_remote_max_slice(n_slices - 1)


def _owned_by(s0, host, n_slices=6):
    return [
        sl
        for sl in range(n_slices)
        if s0.cluster.fragment_nodes("i", sl)[0].host == host
    ]


def _query_json(client, index, q, slices=None, allow_partial=False, headers=None):
    params = {}
    if slices is not None:
        params["slices"] = ",".join(str(s) for s in slices)
    if allow_partial:
        params["allowPartial"] = "true"
    status, data, _ = client._request_meta(
        "POST",
        f"/index/{index}/query",
        query=params or None,
        body=q.encode(),
        headers=headers or {},
    )
    return status, json.loads(data)


COUNT_Q = 'Count(Bitmap(frame="f", rowID=1))'
BITMAP_Q = 'Bitmap(frame="f", rowID=1)'


class TestChaosEndToEnd:
    def test_partial_results_byte_identical_and_fail_fast(self, tmp_path):
        s0, s1 = _two_servers(
            tmp_path, replicas=1, retry_attempts=2, retry_backoff_ms=5
        )
        try:
            _seed_slices(s0, s1)
            lost = _owned_by(s0, s1.host)
            surviving = _owned_by(s0, s0.host)
            assert lost and surviving, "placement must split across nodes"
            c0 = InternalClient(s0.host, timeout=10.0)

            # Fault-free baselines RESTRICTED to the surviving slices.
            st, base_bm = _query_json(c0, "i", BITMAP_Q, slices=surviving)
            assert st == 200
            st, base_ct = _query_json(c0, "i", COUNT_Q, slices=surviving)
            assert st == 200

            s1.close()  # hard-down node; replicas=1 -> its slices are lost

            # Without the flag: fail fast, naming exactly the lost slices.
            st, err = _query_json(c0, "i", COUNT_Q)
            assert st == 500
            assert "slices unavailable" in err["error"]
            assert str(sorted(lost)) in err["error"]

            # With allowPartial: byte-identical to the restricted run,
            # missingSlices exactly the lost ones.
            st, part_bm = _query_json(c0, "i", BITMAP_Q, allow_partial=True)
            assert st == 200
            assert part_bm["partial"] is True
            assert part_bm["missingSlices"] == sorted(lost)
            assert part_bm["results"] == base_bm["results"]

            st, part_ct = _query_json(c0, "i", COUNT_Q, allow_partial=True)
            assert st == 200
            assert part_ct["results"] == base_ct["results"]
            assert part_ct["missingSlices"] == sorted(lost)
        finally:
            with suppress(Exception):
                s0.close()
            with suppress(Exception):
                s1.close()

    def test_breaker_opens_under_faults_then_recovers(self, tmp_path):
        s0, s1 = _two_servers(
            tmp_path,
            replicas=1,
            retry_attempts=1,
            breaker_failure_threshold=3,
            breaker_open_ms=250,
        )
        try:
            _seed_slices(s0, s1)
            c0 = InternalClient(s0.host, timeout=10.0)
            plan = faults.install(
                f"rpc.send:host={s1.host},path=/index/*/query,mode=error"
            )

            # Each query's s1 leg fails once (retry_attempts=1); after
            # the threshold the breaker opens.
            for _ in range(3):
                st, payload = _query_json(
                    c0, "i", COUNT_Q, allow_partial=True
                )
                assert st == 200 and payload.get("partial") is True
            assert s0.resilience.breakers.state(s1.host) == rz.STATE_OPEN

            # Surfaced at /debug/health.
            st, data = c0._request("GET", "/debug/health")
            health = json.loads(data)
            assert health["breakers"][s1.host]["state"] == rz.STATE_OPEN

            # While open: straight to failover, no wire attempt burned.
            hits = plan.rules[0].hits
            st, payload = _query_json(c0, "i", COUNT_Q, allow_partial=True)
            assert st == 200 and payload.get("partial") is True
            assert plan.rules[0].hits == hits

            # Heal the network; after open_ms the half-open probe
            # succeeds, the breaker closes, and results are whole again.
            faults.clear()
            time.sleep(0.3)
            st, payload = _query_json(c0, "i", COUNT_Q, allow_partial=True)
            assert st == 200
            assert "partial" not in payload
            assert payload["results"][0] == 6
            assert s0.resilience.breakers.state(s1.host) == rz.STATE_CLOSED
        finally:
            faults.clear()
            with suppress(Exception):
                s0.close()
            with suppress(Exception):
                s1.close()

    def test_retries_respect_caps(self, tmp_path):
        s0, s1 = _two_servers(
            tmp_path,
            replicas=1,
            retry_attempts=2,
            retry_backoff_ms=5,
            breaker_failure_threshold=100,
        )
        try:
            _seed_slices(s0, s1)
            c0 = InternalClient(s0.host, timeout=10.0)
            plan = faults.install(
                f"rpc.send:host={s1.host},path=/index/*/query,mode=error"
            )
            st, payload = _query_json(c0, "i", COUNT_Q, allow_partial=True)
            assert st == 200 and payload.get("partial") is True
            # Exactly `retry_attempts` wire tries for the failing leg.
            assert plan.rules[0].hits == 2
        finally:
            faults.clear()
            with suppress(Exception):
                s0.close()
            with suppress(Exception):
                s1.close()

    def test_transient_fault_retried_transparently(self, tmp_path):
        s0, s1 = _two_servers(
            tmp_path, replicas=1, retry_attempts=3, retry_backoff_ms=5
        )
        try:
            _seed_slices(s0, s1)
            c0 = InternalClient(s0.host, timeout=10.0)
            plan = faults.install(
                f"rpc.send:host={s1.host},path=/index/*/query,nth=1,mode=error"
            )
            st, payload = _query_json(c0, "i", COUNT_Q)
            assert st == 200
            assert "partial" not in payload
            assert payload["results"][0] == 6
            assert plan.rules[0].hits == 1  # failed once, retried, healed
        finally:
            faults.clear()
            with suppress(Exception):
                s0.close()
            with suppress(Exception):
                s1.close()

    def test_deadline_504_coordinator_and_remote_leg(self, tmp_path):
        s0, s1 = _two_servers(
            tmp_path, replicas=1, retry_attempts=2, retry_backoff_ms=5
        )
        try:
            _seed_slices(s0, s1)
            c0 = InternalClient(s0.host, timeout=10.0)
            c1 = InternalClient(s1.host, timeout=10.0)

            # Coordinator: an expired per-request deadline answers 504
            # with the trace id.
            st, err = _query_json(
                c0, "i", COUNT_Q, headers={rz.DEADLINE_HEADER: "0"}
            )
            assert st == 504
            assert "deadline" in err["error"]
            assert "trace" in err["error"]

            # Remote leg served directly: same contract on any node.
            st, err = _query_json(
                c1, "i", COUNT_Q, headers={rz.DEADLINE_HEADER: "0"}
            )
            assert st == 504

            # Propagation: a delayed remote leg blows the coordinator's
            # budget -> the coordinator 504s (never a bogus failover
            # answer), and the lost budget is not misread as a dead node.
            faults.install(
                f"rpc.recv:host={s1.host},path=/index/*/query,"
                "mode=delay,delay-ms=600"
            )
            t0 = time.monotonic()
            st, err = _query_json(
                c0, "i", COUNT_Q, headers={rz.DEADLINE_HEADER: "200"}
            )
            assert st == 504
            assert "trace" in err["error"]
            assert time.monotonic() - t0 < 5.0
        finally:
            faults.clear()
            with suppress(Exception):
                s0.close()
            with suppress(Exception):
                s1.close()

    def test_device_fault_serves_whole_via_host_fallback(self, tmp_path):
        """An injected device-launch fault no longer turns the node
        into a brick the cluster must route around: the device-health
        layer (device/health.py) classifies the failure, retries once,
        and answers the SAME query byte-identically from the
        authoritative host planes — whole, not partial — and the next
        fault-free query rides the device path again."""
        s0, s1 = _two_servers(
            tmp_path, replicas=1, retry_attempts=1
        )
        try:
            _seed_slices(s0, s1)
            c0 = InternalClient(s0.host, timeout=10.0)
            # Persistent while installed: initial launch + the health
            # layer's single retry both fault on every mapper.
            faults.install("device.launch:mode=error")
            st, payload = _query_json(c0, "i", COUNT_Q, allow_partial=True)
            assert st == 200
            assert payload.get("partial") is not True
            assert payload["results"][0] == 6
            faults.clear()
            st, payload = _query_json(c0, "i", COUNT_Q)
            assert st == 200 and payload["results"][0] == 6
        finally:
            faults.clear()
            with suppress(Exception):
                s0.close()
            with suppress(Exception):
                s1.close()
