"""Device-fault tolerance tests (device/health.py + exec/hosteval.py).

The acceptance bar (ISSUE 15): classified launch failures drive the
healthy → suspect → quarantined state machine with half-open probes; a
quarantined accelerator answers BYTE-IDENTICALLY from the authoritative
host planes (Count/Bitmap algebra, BSI ± predicates, aggregates, TopN);
a coalesced launch failure fails over per-waiter without poisoning the
shared batch; a hung collective trips the launch watchdog instead of
wedging; detached coalesce waiters' batch errors are consumed, not
GC-logged; and an e2e two-node cluster with one node's device flapping
serves zero wrong answers, quarantines, heals through a probe, and
rejoins the device path.
"""

import concurrent.futures
import time
from contextlib import suppress

import numpy as np
import pytest

from pilosa_tpu.cluster.topology import Cluster, new_cluster
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.device import health as health_mod
from pilosa_tpu.device.health import (
    COLLECTIVE,
    KIND_ERROR,
    KIND_HANG,
    KIND_OOM,
    MODE_DENY,
    MODE_OK,
    MODE_PROBE,
    STATE_HEALTHY,
    STATE_QUARANTINED,
    STATE_SUSPECT,
    DeviceHealth,
    LaunchWatchdogTimeout,
)
from pilosa_tpu.exec import Executor, coalesce as coalesce_mod
from pilosa_tpu.exec.coalesce import CoalesceScheduler
from pilosa_tpu.net import resilience as rz
from pilosa_tpu.ops.bitplane import SLICE_WIDTH
from pilosa_tpu.pql.parser import parse_string
from pilosa_tpu.testing import faults


class _Stats:
    def __init__(self):
        self.counts: dict = {}

    def count(self, name, value=1, rate=1.0):
        self.counts[name] = self.counts.get(name, 0) + value

    def count_with_custom_tags(self, name, value, tags):
        key = name + "".join(f"[{t}]" for t in sorted(tags))
        self.counts[key] = self.counts.get(key, 0) + value

    def gauge(self, *a, **k):
        pass

    def histogram(self, *a, **k):
        pass


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def test_classify_kinds():
    assert health_mod.classify(LaunchWatchdogTimeout("x")) == KIND_HANG
    assert health_mod.classify(faults.FaultOOM("injected oom")) == KIND_OOM
    assert health_mod.classify(faults.FaultError("injected")) == KIND_ERROR
    assert (
        health_mod.classify(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        )
        == KIND_OOM
    )
    # Non-device exceptions must re-raise at the launch sites.
    assert health_mod.classify(ValueError("bad frame")) is None
    assert health_mod.classify(rz.DeadlineExceeded("budget")) is None
    assert health_mod.classify(KeyError("x")) is None


def test_classify_xla_shaped_errors():
    class XlaRuntimeError(Exception):
        pass

    XlaRuntimeError.__module__ = "jaxlib.xla_extension"
    assert health_mod.classify(XlaRuntimeError("boom")) == KIND_ERROR
    assert (
        health_mod.classify(XlaRuntimeError("RESOURCE_EXHAUSTED: 1GiB"))
        == KIND_OOM
    )


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


def test_quarantine_threshold_and_halfopen_probe_recovery():
    h = DeviceHealth(
        quarantine_threshold=2, open_ms=80, probe_successes=2, watchdog_ms=0
    )
    p = ["device:0"]
    assert h.acquire(p) == MODE_OK
    h.failure(p, KIND_ERROR)
    assert h.snapshot()["paths"]["device:0"]["state"] == STATE_SUSPECT
    assert h.acquire(p) == MODE_OK  # suspect still launches
    h.failure(p, KIND_ERROR)
    snap = h.snapshot()["paths"]["device:0"]
    assert snap["state"] == STATE_QUARANTINED
    assert h.degraded() and h.snapshot()["degraded"]
    assert h.acquire(p) == MODE_DENY
    time.sleep(0.1)
    # Past the open window: exactly ONE probe is admitted.
    assert h.acquire(p) == MODE_PROBE
    assert h.acquire(p) == MODE_DENY  # probe exclusive
    # Probe succeeds, but probe_successes=2: still quarantined, next
    # probe admitted immediately (no new open wait).
    h.success(p, probe=True)
    assert h.snapshot()["paths"]["device:0"]["state"] == STATE_QUARANTINED
    assert h.acquire(p) == MODE_PROBE
    h.success(p, probe=True)
    assert h.snapshot()["paths"]["device:0"]["state"] == STATE_HEALTHY
    assert h.acquire(p) == MODE_OK
    assert not h.degraded()


def test_failed_probe_rearms_quarantine_clock():
    h = DeviceHealth(quarantine_threshold=1, open_ms=60, watchdog_ms=0)
    p = ["device:0"]
    h.failure(p, KIND_OOM)
    assert h.acquire(p) == MODE_DENY
    time.sleep(0.08)
    assert h.acquire(p) == MODE_PROBE
    h.failure(p, KIND_OOM, probe=True)
    assert h.acquire(p) == MODE_DENY  # clock re-armed
    time.sleep(0.08)
    assert h.acquire(p) == MODE_PROBE


def test_hang_quarantines_immediately_and_success_resets_suspect():
    h = DeviceHealth(quarantine_threshold=5, open_ms=1000, watchdog_ms=0)
    p = ["device:0"]
    h.failure(p, KIND_ERROR)
    h.success(p)
    assert h.snapshot()["paths"]["device:0"]["state"] == STATE_HEALTHY
    assert h.snapshot()["paths"]["device:0"]["consecutiveFailures"] == 0
    h.failure(p, KIND_HANG)  # one hang is enough
    assert h.snapshot()["paths"]["device:0"]["state"] == STATE_QUARANTINED


def test_failure_with_fault_device_narrows_blame():
    h = DeviceHealth(quarantine_threshold=1, watchdog_ms=0)
    paths = ["device:0", "device:1"]
    h.failure(paths, KIND_ERROR, device=1)
    snap = h.snapshot()["paths"]
    assert snap["device:1"]["state"] == STATE_QUARANTINED
    assert "device:0" not in snap or snap["device:0"]["state"] == STATE_HEALTHY


def test_state_change_callback_fires_on_quarantine_and_heal():
    events = []
    h = DeviceHealth(
        quarantine_threshold=1,
        open_ms=40,
        watchdog_ms=0,
        on_state_change=lambda p, s: events.append((p, s)),
    )
    h.failure(["device:0"], KIND_ERROR)
    time.sleep(0.06)
    assert h.acquire(["device:0"]) == MODE_PROBE
    h.success(["device:0"], probe=True)
    assert events == [
        ("device:0", STATE_QUARANTINED),
        ("device:0", STATE_HEALTHY),
    ]


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_trips_and_abandons_then_recovers():
    stats = _Stats()
    r = health_mod._WatchdogRunner(stats=stats)
    try:
        with pytest.raises(LaunchWatchdogTimeout):
            r.run(lambda: time.sleep(0.4) or "late", timeout_s=0.05)
        # A fresh runner serves the next call even while the old one
        # still sleeps.
        assert r.run(lambda: "ok", timeout_s=5.0) == "ok"
        time.sleep(0.45)
        assert stats.counts.get("device.watchdog.abandonedCompletions") == 1
    finally:
        r.close()


def test_run_collective_hang_trips_watchdog_and_quarantines_mesh_path():
    stats = _Stats()
    h = DeviceHealth(watchdog_ms=60, open_ms=50, stats=stats)
    try:
        with pytest.raises(LaunchWatchdogTimeout):
            h.run_collective(lambda: time.sleep(0.3))
        assert stats.counts.get("device.watchdogTrips") == 1
        assert (
            h.snapshot()["paths"][COLLECTIVE]["state"] == STATE_QUARANTINED
        )
        assert not h.collective_allowed()
        with pytest.raises(health_mod.CollectiveUnavailable):
            h.run_collective(lambda: "never runs")
        # Past the open window the next collective IS the probe; wait
        # out the abandoned sleeper so the lock is free again.
        time.sleep(0.3)
        assert h.collective_allowed()
        assert h.run_collective(lambda: 42) == 42
        assert h.snapshot()["paths"][COLLECTIVE]["state"] == STATE_HEALTHY
    finally:
        h.close()


def test_run_collective_error_counts_against_collective_path():
    h = DeviceHealth(watchdog_ms=0, quarantine_threshold=1)
    with pytest.raises(faults.FaultError):
        h.run_collective(lambda: (_ for _ in ()).throw(faults.FaultError("x")))
    assert h.snapshot()["paths"][COLLECTIVE]["state"] == STATE_QUARANTINED
    # Non-device exceptions propagate unrecorded.
    h2 = DeviceHealth(watchdog_ms=0, quarantine_threshold=1)
    with pytest.raises(ValueError):
        h2.run_collective(lambda: (_ for _ in ()).throw(ValueError("x")))
    assert COLLECTIVE not in h2.snapshot()["paths"] or (
        h2.snapshot()["paths"][COLLECTIVE]["state"] == STATE_HEALTHY
    )


# ---------------------------------------------------------------------------
# fault grammar (satellite: kind= + per-device matching)
# ---------------------------------------------------------------------------


def test_fault_kind_grammar_and_validation():
    plan = faults.parse("device.launch:kind=oom,times=1")
    with pytest.raises(faults.FaultOOM):
        plan.check("device.launch")
    plan.check("device.launch")  # times exhausted
    with pytest.raises(faults.FaultSpecError):
        faults.parse("device.launch:kind=frobnicate")
    with pytest.raises(faults.FaultSpecError):
        faults.parse("rpc.send:kind=oom")
    with pytest.raises(faults.FaultSpecError):
        faults.parse("rpc.recv:device=1")


def test_fault_per_device_matching():
    plan = faults.parse("device.launch:kind=error,device=3")
    plan.check("device.launch", device=2)  # no fire
    plan.check("device.launch")  # no device info: no fire
    with pytest.raises(faults.FaultError):
        plan.check("device.launch", device=3)
    assert plan.rules[0].hits == 1


def test_fault_hang_sleeps_then_returns():
    plan = faults.parse("device.launch:kind=hang,delay-ms=30,times=1")
    t0 = time.monotonic()
    plan.check("device.launch")  # returns (after the sleep), no raise
    assert time.monotonic() - t0 >= 0.025


# ---------------------------------------------------------------------------
# executor: host fallback byte-identity + quarantine/heal
# ---------------------------------------------------------------------------

BSI_MIN, BSI_MAX = -128, 127


def _seed(holder, rng):
    idx = holder.create_index("i")
    f = idx.create_frame("f", cache_size=64)
    bits = [
        (1, 0), (1, 3), (1, SLICE_WIDTH + 1), (1, 2 * SLICE_WIDTH + 5),
        (2, 3), (2, SLICE_WIDTH + 1), (2, SLICE_WIDTH + 9),
        (3, 7), (3, 2 * SLICE_WIDTH + 5), (4, 11), (4, SLICE_WIDTH + 2),
    ]
    for row, col in bits:
        f.set_bit("standard", row, col)
    f.set_options(range_enabled=True)
    f.create_field("v", BSI_MIN, BSI_MAX)
    for col in range(0, 3 * SLICE_WIDTH, SLICE_WIDTH // 7):
        f.import_value("v", [col], [int(rng.integers(BSI_MIN, BSI_MAX + 1))])
    ft = idx.create_frame("t", cache_size=64)
    for row in range(6):
        for col in range(0, 2 * SLICE_WIDTH, SLICE_WIDTH // (5 + row)):
            ft.set_bit("standard", row, col)


MIXED = [
    "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))",
    "Count(Union(Bitmap(rowID=1, frame=f), Bitmap(rowID=3, frame=f)))",
    "Count(Difference(Bitmap(rowID=2, frame=f), Bitmap(rowID=4, frame=f)))",
    "Bitmap(rowID=1, frame=f)",
    "Union(Bitmap(rowID=2, frame=f), Bitmap(rowID=3, frame=f))",
    f"Count(Range(frame=f, v > {BSI_MIN}))",
    f"Count(Range(frame=f, v <= {BSI_MAX}))",
    "Count(Range(frame=f, v == 0))",
    f"Count(Range(frame=f, v >< [{BSI_MIN}, {BSI_MAX}]))",
    "Count(Intersect(Bitmap(rowID=1, frame=f), Range(frame=f, v < -5)))",
    "Sum(frame=f, field=v)",
    "Sum(Bitmap(rowID=1, frame=f), frame=f, field=v)",
    "Min(frame=f, field=v)",
    "Max(frame=f, field=v)",
    "TopN(Bitmap(rowID=0, frame=t), frame=t, n=3)",
    "TopN(frame=t, n=2)",
]


def _canon(result):
    if hasattr(result, "bits"):
        return ("bits", tuple(result.bits()))
    if isinstance(result, list):
        return ("pairs", tuple((p.id, p.count) for p in result))
    if hasattr(result, "value"):
        return ("valcount", int(result.value), int(result.count))
    if result is None:
        return ("none",)
    return ("val", int(result))


def _run_all(ex, queries=MIXED):
    return [_canon(ex.execute("i", parse_string(q))[0]) for q in queries]


def test_quarantined_device_serves_byte_identical_from_host(holder, rng):
    _seed(holder, rng)
    c = new_cluster(1)
    host = c.nodes[0].host
    plain = Executor(holder, host=host, cluster=c)
    try:
        expected = _run_all(plain)
    finally:
        plain.close()

    dh = DeviceHealth(quarantine_threshold=1, open_ms=3600_000, watchdog_ms=0)
    ex = Executor(holder, host=host, cluster=c, device_health=dh)
    try:
        # Force full quarantine: every device path + the collective.
        dh.failure(dh.device_paths() + [COLLECTIVE], KIND_OOM)
        assert dh.degraded()
        got = _run_all(ex)
        assert got == expected
        # Still quarantined (open window is an hour): every answer above
        # came from the host evaluator.
        assert dh.degraded()
        assert (
            ex.holder.stats is not None
        )  # stats path exercised via hosteval counters
    finally:
        ex.close()
        dh.close()


def test_persistent_fault_quarantines_then_heals_through_probe(holder, rng):
    _seed(holder, rng)
    c = new_cluster(1)
    host = c.nodes[0].host
    plain = Executor(holder, host=host, cluster=c)
    try:
        expected = _run_all(plain)
    finally:
        plain.close()

    dh = DeviceHealth(quarantine_threshold=2, open_ms=120, watchdog_ms=0)
    ex = Executor(holder, host=host, cluster=c, device_health=dh)
    try:
        faults.install("device.launch:mode=error")
        # Every query answers correctly despite the persistent fault
        # (retry -> failure -> host fallback), and the state machine
        # walks suspect -> quarantined.
        got = _run_all(ex)
        assert got == expected
        assert dh.degraded()
        # Clear the fault, wait out the open window: the next query IS
        # the half-open probe, succeeds on device, and heals the path.
        faults.clear()
        time.sleep(0.15)
        got = _run_all(ex)
        assert got == expected
        assert not dh.degraded()
        snap = ex.device_health.snapshot()
        assert snap["paths"]["device:0"]["state"] == STATE_HEALTHY
        assert snap["paths"]["device:0"]["quarantines"] >= 1
    finally:
        ex.close()
        dh.close()


def test_coalesced_fault_fails_over_per_waiter(holder, rng):
    """A persistent fault under a CONCURRENT distinct-query storm
    through the coalescer: every waiter fails over to the host path
    independently — zero wrong answers — and the shared scheduler keeps
    serving cleanly after the fault clears."""
    _seed(holder, rng)
    c = new_cluster(1)
    host = c.nodes[0].host
    plain = Executor(holder, host=host, cluster=c)
    try:
        expected = _run_all(plain)
    finally:
        plain.close()

    dh = DeviceHealth(quarantine_threshold=3, open_ms=100, watchdog_ms=0)
    co = CoalesceScheduler(max_wait_us=100_000, health=dh)
    ex = Executor(holder, host=host, cluster=c, coalescer=co, device_health=dh)
    try:
        faults.install("device.launch:mode=error")

        def run_mix(t):
            order = list(range(t, len(MIXED))) + list(range(t))
            got = [None] * len(MIXED)
            for i in order:
                got[i] = _canon(ex.execute("i", parse_string(MIXED[i]))[0])
            return got

        with concurrent.futures.ThreadPoolExecutor(6) as pool:
            for got in pool.map(run_mix, range(6)):
                assert got == expected
        assert dh.degraded()
        faults.clear()
        time.sleep(0.13)
        assert _run_all(ex) == expected
        assert not dh.degraded()
    finally:
        ex.close()
        co.close()
        dh.close()


# ---------------------------------------------------------------------------
# abandoned-waiter error consumption (satellite bugfix)
# ---------------------------------------------------------------------------


def test_abandoned_coalesce_error_is_consumed_and_counted():
    stats = _Stats()
    co = CoalesceScheduler(max_wait_us=0)
    try:
        # A float batch makes the shared launch's popcount fail AFTER
        # submission — the shape of a batch error landing once every
        # waiter has detached on deadline expiry.
        batch = np.zeros((2, 2, 8), dtype=np.float32)
        fut = co.submit(
            ("Intersect", ("leaf", 0), ("leaf", 1)), "count", batch
        )
        # The waiter detaches (deadline): it consumes the eventual
        # error via the done-callback instead of ever calling result().
        fut.add_done_callback(coalesce_mod.consume_abandoned(stats))
        deadline = time.monotonic() + 10
        while not fut.done() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fut.done()
        assert stats.counts.get("exec.coalesce.abandonedErrors") == 1
        # The exception WAS retrieved: the future's GC path will not
        # log "exception was never retrieved".
        assert fut.exception(timeout=0) is not None
    finally:
        co.close()


# ---------------------------------------------------------------------------
# degraded-replica deprioritization
# ---------------------------------------------------------------------------


def test_slices_by_node_prefers_non_degraded_replica(tmp_path):
    h = Holder(str(tmp_path / "d"))
    h.open()
    try:
        cluster = Cluster(replica_n=2)
        cluster.add_node("127.0.0.1:1")
        cluster.add_node("127.0.0.1:2")
        h.create_index("i")
        ex = Executor(h, host="127.0.0.1:1", cluster=cluster)
        try:
            slices = [0, 1, 2, 3]
            base = ex._slices_by_node(cluster.nodes, "i", slices)
            # With replicas=2 both nodes own every slice; the primary
            # wins by default, so both hosts normally appear.
            assert sum(len(v[1]) for v in base.values()) == len(slices)
            # Degrade node 1: everything routes to node 2 (the healthy
            # replica), and the health version bump invalidates the
            # routing cache.
            assert cluster.note_degraded("127.0.0.1:1", True)
            m = ex._slices_by_node(cluster.nodes, "i", slices)
            assert set(m) == {"127.0.0.1:2"}
            # Both degraded: fall back to primary-order routing.
            assert cluster.note_degraded("127.0.0.1:2", True)
            m = ex._slices_by_node(cluster.nodes, "i", slices)
            assert m.keys() == base.keys()
            # Healing flips back.
            assert cluster.note_degraded("127.0.0.1:1", False)
            assert cluster.note_degraded("127.0.0.1:2", False)
            m = ex._slices_by_node(cluster.nodes, "i", slices)
            assert m.keys() == base.keys()
            assert not cluster.note_degraded("127.0.0.1:2", False)  # no-op
        finally:
            ex.close()
    finally:
        h.close()


# ---------------------------------------------------------------------------
# e2e: two nodes, one device flapping
# ---------------------------------------------------------------------------


def _two_servers(tmp_path):
    from pilosa_tpu.net.server import Server
    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    hosts = sorted(f"127.0.0.1:{free_port()}" for _ in range(2))
    kw = dict(
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        query_timeout_ms=30_000.0,
        retry_attempts=1,
        quarantine_threshold=2,
        quarantine_open_ms=200.0,
        launch_watchdog_ms=0.0,
        admission=False,
    )

    def make(name, host):
        cluster = Cluster(replica_n=1)
        s = Server(
            data_dir=str(tmp_path / name), host=host, cluster=cluster, **kw
        )
        s.open()
        for hh in hosts:
            if cluster.node_by_host(hh) is None:
                cluster.add_node(hh)
        cluster.nodes.sort(key=lambda n: n.host)
        return s

    s0, s1 = make("n0", hosts[0]), make("n1", hosts[1])
    for s in (s0, s1):
        s.holder.create_index_if_not_exists("i")
        s.holder.index("i").create_frame_if_not_exists("f")
    return s0, s1


@pytest.mark.slow
def test_e2e_two_node_storm_with_flapping_device(tmp_path):
    """One node's device flaps under a mixed storm: zero wrong answers
    (the degraded node serves via host fallback), its /debug/health
    shows the quarantine, it heals after the fault clears, and rejoins
    the device path."""
    import json

    from pilosa_tpu.net.client import InternalClient

    s0, s1 = _two_servers(tmp_path)
    try:
        n_slices = 4
        for sl in range(n_slices):
            owner = s0.cluster.fragment_nodes("i", sl)[0].host
            srv = s0 if owner == s0.host else s1
            for row in (1, 2):
                srv.holder.frame("i", "f").set_bit(
                    "standard", row, sl * SLICE_WIDTH + row
                )
            srv.holder.frame("i", "f").set_bit(
                "standard", 1, sl * SLICE_WIDTH + 7
            )
        for s in (s0, s1):
            s.holder.index("i").set_remote_max_slice(n_slices - 1)

        queries = [
            "Count(Bitmap(rowID=1, frame=f))",
            "Count(Union(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))",
            "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))",
        ]
        c1 = InternalClient(s1.host, timeout=15.0)
        c0 = InternalClient(s0.host, timeout=15.0)

        def health(client):
            status, data = client._request("GET", "/debug/health")
            assert status == 200
            return json.loads(data)

        want = [c1.execute_pql("i", q) for q in queries]
        assert want[0] == 2 * n_slices

        # Flap node 0's device only: every query through the healthy
        # coordinator must stay byte-identical while node 0 degrades.
        faults.install(f"device.launch:mode=error,host={s0.host}")
        for _round in range(4):
            got = [c1.execute_pql("i", q) for q in queries]
            assert got == want
        snap0 = health(c0)
        assert snap0["device"]["degraded"] is True
        states = {
            p: st["state"] for p, st in snap0["device"]["paths"].items()
        }
        assert STATE_QUARANTINED in states.values()
        # The healthy node never degraded.
        assert health(c1)["device"]["degraded"] is False

        # Heal: clear the fault, wait out the open window; the next
        # query through node 0 is the half-open probe.
        faults.clear()
        time.sleep(0.25)
        got = [c1.execute_pql("i", q) for q in queries]
        assert got == want
        snap0 = health(c0)
        assert snap0["device"]["degraded"] is False
    finally:
        faults.clear()
        with suppress(Exception):
            s0.close()
        with suppress(Exception):
            s1.close()
