"""Tiered storage (pilosa_tpu/tier): object-store backends, demand
hydration, LRU demotion under a disk budget, time-quantum retention,
self-verifying fragment tars, and cold-boot-from-store-alone over real
HTTP nodes."""

from __future__ import annotations

import io
import json
import os
import tarfile
import threading
from datetime import datetime

import pytest

from pilosa_tpu.core.fragment import (
    ArchiveChecksumError,
    FragmentRetiredError,
)
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.net.client import ClientError, InternalClient
from pilosa_tpu.net.server import Server
from pilosa_tpu.ops.bitplane import SLICE_WIDTH
from pilosa_tpu.tier import (
    HydrationError,
    LocalFSStore,
    TierManager,
    fragment_store_key,
    open_store,
    parse_fragment_store_key,
)
from pilosa_tpu.tier.store import StoreChecksumError, StoreError, _ServedStore


def make_holder(tmp_path, name="data") -> Holder:
    h = Holder(str(tmp_path / name))
    h.open()
    return h


def seeded_frame(holder, n_bits=300, rows=5):
    idx = holder.create_index_if_not_exists("i")
    fr = idx.create_frame_if_not_exists("f")
    for c in range(n_bits):
        fr.set_bit("standard", c % rows, c)
    return fr


# ---------------------------------------------------------------------------
# object store backends
# ---------------------------------------------------------------------------


class TestLocalFSStore:
    def test_roundtrip_and_meta(self, tmp_path):
        s = LocalFSStore(str(tmp_path / "store"))
        meta = s.put("fragments/i/f/standard/0.tar", b"hello", extra={"x": 1})
        assert meta.size == 5
        assert s.get("fragments/i/f/standard/0.tar") == b"hello"
        got = s.get_meta("fragments/i/f/standard/0.tar")
        assert got.extra == {"x": 1}
        assert got.sha256 == meta.sha256
        assert [m.key for m in s.list("fragments/")] == [
            "fragments/i/f/standard/0.tar"
        ]
        assert s.delete("fragments/i/f/standard/0.tar")
        assert not s.delete("fragments/i/f/standard/0.tar")
        assert s.get_meta("fragments/i/f/standard/0.tar") is None

    def test_get_rejects_corrupt_content_with_named_error(self, tmp_path):
        s = LocalFSStore(str(tmp_path / "store"))
        s.put("k/v.tar", b"payload")
        with open(tmp_path / "store" / "k" / "v.tar", "wb") as f:
            f.write(b"rotted!")
        with pytest.raises(StoreChecksumError):
            s.get("k/v.tar")

    def test_key_validation(self, tmp_path):
        s = LocalFSStore(str(tmp_path / "store"))
        for bad in ("", "/abs", "a/../b", "x.pmeta", "a//b"):
            with pytest.raises(StoreError):
                s.put(bad, b"")

    def test_missing_object_raises(self, tmp_path):
        s = LocalFSStore(str(tmp_path / "store"))
        with pytest.raises(StoreError):
            s.get("nope/nothing.tar")


class TestHTTPStore:
    def test_roundtrip_over_real_http(self, tmp_path):
        with _ServedStore(str(tmp_path / "store")) as url:
            s = open_store(url)
            s.put("a/b.tar", b"data", extra={"checksum": "ff"})
            assert s.get("a/b.tar") == b"data"
            assert s.get_meta("a/b.tar").extra == {"checksum": "ff"}
            assert s.get_meta("a/missing.tar") is None
            assert [m.key for m in s.list("a/")] == ["a/b.tar"]
            assert s.delete("a/b.tar")
            assert not s.delete("a/b.tar")

    def test_server_rejects_torn_upload(self, tmp_path):
        from pilosa_tpu.tier.store import SHA_HEADER
        import http.client

        with _ServedStore(str(tmp_path / "store")) as url:
            host = url[len("http://"):]
            conn = http.client.HTTPConnection(host, timeout=10)
            conn.request(
                "PUT", "/k.tar", body=b"bytes", headers={SHA_HEADER: "0" * 64}
            )
            resp = conn.getresponse()
            assert resp.status == 422
            conn.close()

    def test_down_store_fails_fast_and_loud(self, tmp_path):
        from pilosa_tpu.net import resilience as rz

        s = open_store(
            "http://127.0.0.1:1",  # nothing listens here
            retry=rz.RetryPolicy(attempts=1, backoff=0.001),
        )
        with pytest.raises(OSError):
            s.get("a/b.tar")


# ---------------------------------------------------------------------------
# self-verifying fragment tars (satellite: embedded checksums)
# ---------------------------------------------------------------------------


class TestArchiveChecksums:
    def _tar(self, holder) -> bytes:
        frag = holder.fragment("i", "f", "standard", 0)
        buf = io.BytesIO()
        frag.write_to(buf)
        return buf.getvalue()

    def test_archive_carries_checksum_entry_first(self, tmp_path):
        holder = make_holder(tmp_path)
        seeded_frame(holder)
        tf = tarfile.open(fileobj=io.BytesIO(self._tar(holder)))
        names = tf.getnames()
        assert names[0] == "checksum"
        doc = json.loads(tf.extractfile("checksum").read())
        assert set(doc["entries"]) == {"data", "cache"}

    def test_roundtrip_restores_identical_content(self, tmp_path):
        holder = make_holder(tmp_path)
        seeded_frame(holder)
        raw = self._tar(holder)
        other = make_holder(tmp_path, "other")
        fr = other.create_index("i").create_frame("f")
        frag = fr.create_view_if_not_exists("standard").create_fragment_if_not_exists(0)
        frag.read_from(io.BytesIO(raw))
        assert frag.count() == holder.fragment("i", "f", "standard", 0).count()
        assert (
            frag.checksum()
            == holder.fragment("i", "f", "standard", 0).checksum()
        )

    @staticmethod
    def _flip_data_byte(raw: bytes) -> bytes:
        """Corrupt one byte INSIDE the data member's payload (tar
        padding between members is not covered by the checksums)."""
        tf = tarfile.open(fileobj=io.BytesIO(raw))
        member = tf.getmember("data")
        out = bytearray(raw)
        out[member.offset_data + 16] ^= 0xFF
        return bytes(out)

    def test_torn_payload_rejected_without_installing(self, tmp_path):
        holder = make_holder(tmp_path)
        seeded_frame(holder)
        raw = self._flip_data_byte(self._tar(holder))
        other = make_holder(tmp_path, "other")
        fr = other.create_index("i").create_frame("f")
        frag = fr.create_view_if_not_exists("standard").create_fragment_if_not_exists(0)
        before = frag.count()
        with pytest.raises(ArchiveChecksumError):
            frag.read_from(io.BytesIO(raw))
        assert frag.count() == before  # nothing half-installed

    def test_legacy_tar_without_checksum_still_restores(self, tmp_path):
        holder = make_holder(tmp_path)
        seeded_frame(holder)
        tf = tarfile.open(fileobj=io.BytesIO(self._tar(holder)))
        out = io.BytesIO()
        tw = tarfile.open(fileobj=out, mode="w|")
        for name in ("data", "cache"):  # strip the checksum entry
            payload = tf.extractfile(name).read()
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tw.addfile(info, io.BytesIO(payload))
        tw.close()
        other = make_holder(tmp_path, "other")
        fr = other.create_index("i").create_frame("f")
        frag = fr.create_view_if_not_exists("standard").create_fragment_if_not_exists(0)
        frag.read_from(io.BytesIO(out.getvalue()))
        assert frag.count() == 300

    def test_http_restore_rejects_torn_tar_with_422(self, tmp_path):
        holder = make_holder(tmp_path)
        seeded_frame(holder)
        raw = self._flip_data_byte(self._tar(holder))
        with Server(
            data_dir=str(tmp_path / "srv"), host="127.0.0.1:0", prewarm=False,
            anti_entropy_interval=3600, polling_interval=3600,
            cache_flush_interval=3600,
        ) as s:
            c = InternalClient(s.host)
            c.create_index("i")
            c.create_frame("i", "f")
            with pytest.raises(ClientError) as ei:
                c.restore_slice("i", "f", "standard", 0, raw)
            assert ei.value.status == 422
            assert "torn" in str(ei.value)


# ---------------------------------------------------------------------------
# TierManager: hydration / demotion / budget
# ---------------------------------------------------------------------------


class TestHydrationAndDemotion:
    def _managed(self, tmp_path, **kwargs):
        store = LocalFSStore(str(tmp_path / "store"))
        holder = make_holder(tmp_path)
        fr = seeded_frame(holder)
        mgr = TierManager(holder, store, **kwargs)
        mgr.attach_all()
        return holder, fr, mgr

    def test_demote_then_first_touch_hydrates(self, tmp_path):
        holder, fr, mgr = self._managed(tmp_path)
        view = fr.view("standard")
        assert mgr.demote(view, 0)
        assert view.cold_slices() == {0}
        assert not os.path.exists(os.path.join(view.fragments_path, "0"))
        # metadata still resident: the slice is visible to planners
        assert view.fragment_slices() == {0}
        assert view.max_slice() == 0
        frag = view.fragment(0)  # first touch
        assert frag is not None and frag.count() == 300
        assert view.cold_slices() == set()
        key = fragment_store_key("i", "f", "standard", 0)
        assert mgr.snapshot()["fragments"][key]["history"][-3:] == [
            "cold", "hydrating", "hot",
        ]

    def test_demotion_aborts_when_a_write_races_the_upload(self, tmp_path):
        holder, fr, mgr = self._managed(tmp_path)
        view = fr.view("standard")
        frag = view.fragment(0)
        version = frag._version
        meta = mgr.upload_fragment(frag)
        frag.set_bit(7, 77)  # lands after the snapshot
        popped = view.demote_fragment(
            0, meta, expect=frag, expect_version=version
        )
        assert popped is None  # stayed hot: the upload is stale
        assert view.fragment(0) is frag

    def test_write_to_retired_fragment_revives_by_hydration(self, tmp_path):
        holder, fr, mgr = self._managed(tmp_path)
        view = fr.view("standard")
        frag = view.fragment(0)
        assert mgr.demote(view, 0)
        # a writer that captured the fragment before the demotion
        with pytest.raises(FragmentRetiredError):
            frag.set_bit(1, 1)
        # the view-level write revives through hydration, losing nothing
        assert view.set_bit(99, 42) is True
        assert view.fragment(0).count() == 301
        assert view.fragment(0).contains(99, 42)

    def test_hydration_failure_is_loud(self, tmp_path):
        holder, fr, mgr = self._managed(tmp_path)
        view = fr.view("standard")
        assert mgr.demote(view, 0)
        mgr.store.delete(fragment_store_key("i", "f", "standard", 0))
        with pytest.raises(HydrationError):
            view.fragment(0)
        # and a write cannot silently create an empty shadow either
        with pytest.raises(HydrationError):
            view.set_bit(0, 0)

    def test_concurrent_first_touch_hydrates_once(self, tmp_path):
        holder, fr, mgr = self._managed(tmp_path)
        view = fr.view("standard")
        assert mgr.demote(view, 0)
        results, errors = [], []

        def touch():
            try:
                results.append(view.fragment(0))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=touch) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len({id(f) for f in results}) == 1  # one install, shared

    def test_disk_budget_demotes_lru(self, tmp_path):
        store = LocalFSStore(str(tmp_path / "store"))
        holder = make_holder(tmp_path)
        idx = holder.create_index("i")
        fr = idx.create_frame("f")
        for s in range(3):
            for c in range(100):
                fr.set_bit("standard", c % 3, s * SLICE_WIDTH + c)
        mgr = TierManager(holder, store, disk_budget_bytes=1)
        mgr.attach_all()
        view = fr.view("standard")
        # establish LRU: slice 2 touched most recently
        view.fragment(0)
        view.fragment(1)
        view.fragment(2)
        demoted = mgr.enforce_disk_budget()
        assert demoted == 3  # budget of 1 byte: everything demotes
        assert view.cold_slices() == {0, 1, 2}
        # queries transparently hydrate back — byte-identical content
        assert view.fragment(1).count() == 100

    def test_hydrate_throttle_paces_reads(self, tmp_path):
        import time as _time

        holder, fr, mgr = self._managed(
            tmp_path, hydrate_throttle_mbps=0.05
        )  # ~6.25 KB/s
        view = fr.view("standard")
        # The gate charges each read against the NEXT (bursts of one
        # are free; the sustained rate is what's bounded): the second
        # hydration must wait out the first read's debt.
        assert mgr.demote(view, 0)
        view.fragment(0)
        assert mgr.demote(view, 0)
        t0 = _time.monotonic()
        view.fragment(0)
        # the fragment tar is a few KB at ~6 KB/s: visible pacing
        assert _time.monotonic() - t0 > 0.2


# ---------------------------------------------------------------------------
# retention (satellite: time-quantum TTL)
# ---------------------------------------------------------------------------


class TestRetention:
    def _frame_with_history(self, tmp_path):
        store = LocalFSStore(str(tmp_path / "store"))
        holder = make_holder(tmp_path)
        idx = holder.create_index("i")
        fr = idx.create_frame("f", time_quantum="YMD")
        for c in range(40):
            fr.set_bit("standard", 1, c, t=datetime(2024, 1, 1, 12))
            fr.set_bit("standard", 2, c, t=datetime(2024, 3, 1, 12))
        return store, holder, fr

    def test_parse_time_view(self):
        assert tq.parse_time_view("standard_2024") == (
            "standard", datetime(2024, 1, 1), "Y",
        )
        assert tq.parse_time_view("standard_20240301") == (
            "standard", datetime(2024, 3, 1), "D",
        )
        assert tq.parse_time_view("standard") is None
        assert tq.parse_time_view("standard_abc") is None

    def test_sweep_ages_exact_view_sets_then_deletes(self, tmp_path):
        store, holder, fr = self._frame_with_history(tmp_path)
        mgr = TierManager(
            holder, store,
            retention_age_s=30 * 86400.0,
            retention_delete_s=90 * 86400.0,
        )
        mgr.attach_all()
        out = mgr.sweep_retention(now=datetime(2024, 4, 15))
        # Jan 1 D-view (ended Jan 2, ~104d): DELETED.  Jan M-view
        # (ended Feb 1, ~74d) and Mar 1 D-view (ended Mar 2, ~44d):
        # aged to the store.  Mar M-view (ended Apr 1, 14d) and the
        # Y-view (still open): untouched.
        assert out == {"aged": 2, "deleted": 1}
        assert fr.view("standard_20240101") is None
        v = fr.view("standard_202401")
        assert v is not None and v.cold_slices() == {0}
        assert fr.view("standard_202403").cold_slices() == set()
        # deleted view's store object is gone too
        assert (
            store.get_meta(
                fragment_store_key("i", "f", "standard_20240101", 0)
            )
            is None
        )
        # aged view still answers queries by hydration
        assert fr.view("standard_202401").fragment(0).count() == 40

    def test_racing_writer_to_expired_view_revives(self, tmp_path):
        store, holder, fr = self._frame_with_history(tmp_path)
        mgr = TierManager(holder, store, retention_age_s=86400.0)
        mgr.attach_all()
        mgr.sweep_retention(now=datetime(2024, 6, 1))
        v = fr.view("standard_20240101")
        assert v.cold_slices() == {0}
        # a write to the aged view hydrates it back and lands — old
        # bits intact, new bit present, nothing silently lost
        fr.set_bit("standard", 5, 7, t=datetime(2024, 1, 1, 9))
        assert v.fragment(0).count() == 41
        assert v.fragment(0).contains(5, 7)

    def test_per_frame_override_beats_global(self, tmp_path):
        store, holder, fr = self._frame_with_history(tmp_path)
        fr.set_options(retention_age_s=10 * 365 * 86400.0)  # effectively off
        mgr = TierManager(holder, store, retention_age_s=86400.0)
        mgr.attach_all()
        out = mgr.sweep_retention(now=datetime(2024, 6, 1))
        assert out == {"aged": 0, "deleted": 0}

    def test_frame_meta_persists_retention(self, tmp_path):
        holder = make_holder(tmp_path)
        fr = holder.create_index("i").create_frame("f", time_quantum="YMD")
        fr.set_options(retention_age_s=5.0, retention_delete_s=9.0)
        holder.close()
        holder2 = make_holder(tmp_path)
        fr2 = holder2.frame("i", "f")
        assert fr2.retention_age_s == 5.0
        assert fr2.retention_delete_s == 9.0


# ---------------------------------------------------------------------------
# cold boot from the store alone (satellite: byte-identical serving)
# ---------------------------------------------------------------------------


def _quiet_server(tmp_path, name, store_url, **kwargs) -> Server:
    return Server(
        data_dir=str(tmp_path / name),
        host="127.0.0.1:0",
        tier_store=store_url,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        tier_sweep_interval_s=3600,
        prewarm=False,
        **kwargs,
    )


class TestColdBoot:
    @pytest.mark.slow
    def test_cold_boot_serves_byte_identical_results(self, tmp_path):
        store_url = str(tmp_path / "store")
        donor = _quiet_server(tmp_path, "donor", store_url)
        donor.open()
        c0 = InternalClient(donor.host)
        c0.create_index("i")
        c0.create_frame("i", "f", {"rangeEnabled": True})
        c0.create_field("i", "f", "val", 0, 1000)
        bits = [
            (c % 11, c)
            for c in range(2 * SLICE_WIDTH - 400, 2 * SLICE_WIDTH + 400)
        ]
        for s in (1, 2):
            c0.import_bits(
                "i", "f", s, [b for b in bits if b[1] // SLICE_WIDTH == s]
            )
        c0.import_value(
            "i", "f", "val", 1,
            [2 * SLICE_WIDTH - 10, 2 * SLICE_WIDTH - 5], [7, 900],
        )
        queries = [
            'Count(Bitmap(frame="f", rowID=1))',
            'Count(Union(Bitmap(frame="f", rowID=1), Bitmap(frame="f", rowID=2)))',
            'TopN(frame="f", n=5)',
            'Count(Range(frame="f", val > 5))',
        ]
        want = [c0.execute_pql("i", q) for q in queries]
        assert donor.tier.upload_all() == 3
        donor.close()

        cold = _quiet_server(tmp_path, "empty", store_url)
        cold.open()
        try:
            c1 = InternalClient(cold.host)
            snap = json.loads(
                c1._check(*c1._request("GET", "/debug/tier"))
            )
            assert snap["fragments"], "bootstrap must register cold fragments"
            assert all(
                v["state"] == "cold" for v in snap["fragments"].values()
            )
            got = [c1.execute_pql("i", q) for q in queries]
            for q, w, g in zip(queries, want, got):
                if hasattr(w, "__iter__"):
                    w = [(p.id, p.count) for p in w]
                    g = [(p.id, p.count) for p in g]
                assert g == w, f"{q}: {g} != {w}"
            snap = json.loads(
                c1._check(*c1._request("GET", "/debug/tier"))
            )
            hot = {
                k for k, v in snap["fragments"].items() if v["state"] == "hot"
            }
            assert hot, "demand hydration must have engaged"
            for v in snap["fragments"].values():
                if v["state"] == "hot":
                    assert v["history"][-3:] == ["cold", "hydrating", "hot"]
        finally:
            cold.close()

    @pytest.mark.slow
    def test_store_riding_rebalance_copy(self, tmp_path):
        """A joining node restores slices from the object store instead
        of peer streams when the store holds fresh checksums."""
        from pilosa_tpu.cluster.topology import Cluster

        store_url = str(tmp_path / "store")

        from pilosa_tpu.obs.stats import ExpvarStatsClient

        def make(name, hosts):
            cl = Cluster()
            for h in hosts:
                cl.add_node(h)
            return _quiet_server(
                tmp_path, name, store_url, cluster=cl,
                stats=ExpvarStatsClient(),
            )

        a = make("a", [])
        a.open()
        b = make("b", [a.host])  # joining node: not in the ring
        b.open()
        try:
            ca = InternalClient(a.host)
            ca.create_index("i")
            ca.create_frame("i", "f")
            for s in range(3):
                ca.import_bits(
                    "i", "f", s,
                    [(c % 7, s * SLICE_WIDTH + c) for c in range(120)],
                )
            count_before = ca.execute_pql(
                "i", 'Count(Bitmap(frame="f", rowID=1))'
            )
            a.tier.upload_all()
            st, data = ca._request(
                "POST", "/cluster/resize",
                body=json.dumps({"hosts": sorted([a.host, b.host])}).encode(),
            )
            ca._check(st, data)
            deadline = 30.0
            import time as _time

            t0 = _time.monotonic()
            while _time.monotonic() - t0 < deadline:
                snap = json.loads(
                    ca._check(*ca._request("GET", "/debug/rebalance"))
                )
                if not snap.get("running") and snap.get("transition") is None:
                    break
                _time.sleep(0.2)
            else:
                pytest.fail(f"resize did not complete: {snap}")
            assert not snap.get("lastError"), snap
            # the copy rode the store, not peer streams
            vars_b = json.loads(
                ca._check(*ca._request("GET", "/debug/vars"))
            )
            counts = (vars_b.get("stats") or {}).get("counts", {})
            store_restores = sum(
                v for k, v in counts.items()
                if k.startswith("cluster.rebalance.storeRestores")
            )
            assert store_restores > 0, counts
            assert (
                ca.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))')
                == count_before
            )
        finally:
            b.close()
            a.close()


# ---------------------------------------------------------------------------
# store-key helpers / config plumbing
# ---------------------------------------------------------------------------


class TestPlumbing:
    def test_fragment_store_key_roundtrip(self):
        key = fragment_store_key("idx", "fr", "standard_2024", 7)
        assert key == "fragments/idx/fr/standard_2024/7.tar"
        assert parse_fragment_store_key(key) == ("idx", "fr", "standard_2024", 7)
        assert parse_fragment_store_key("fragments/short.tar") is None
        assert parse_fragment_store_key("schema.json") is None

    def test_config_tier_section(self):
        from pilosa_tpu import config as config_mod

        cfg = config_mod.from_toml(
            "[tier]\n"
            'store = "file:///tmp/s"\n'
            "hydrate-throttle-mbps = 80\n"
            "disk-budget-bytes = 1048576\n"
            "retention-age-s = 3600\n"
            "retention-delete-s = 7200\n"
            "sweep-interval-s = 5\n"
        )
        cfg.validate()
        assert cfg.tier.store == "file:///tmp/s"
        assert cfg.tier.hydrate_throttle_mbps == 80.0
        assert cfg.tier.disk_budget_bytes == 1 << 20
        # env overlay
        cfg2 = config_mod.apply_env(
            config_mod.Config(),
            {"PILOSA_TIER_STORE": "/x", "PILOSA_TIER_DISK_BUDGET_BYTES": "42"},
        )
        assert cfg2.tier.store == "/x"
        assert cfg2.tier.disk_budget_bytes == 42
        # round-trips through to_toml
        assert "[tier]" in config_mod.Config().to_toml()

    def test_config_rejects_bad_retention(self):
        from pilosa_tpu import config as config_mod

        cfg = config_mod.Config()
        cfg.tier.store = "/s"
        cfg.tier.retention_age_s = 100.0
        cfg.tier.retention_delete_s = 50.0
        with pytest.raises(config_mod.ConfigError):
            cfg.validate()
        cfg2 = config_mod.Config()
        cfg2.tier.retention_delete_s = 10.0  # delete without a store
        with pytest.raises(config_mod.ConfigError):
            cfg2.validate()
