"""Elastic-cluster rebalancing: versioned topology epochs, the
migration planner / delta log units, and end-to-end live resize over
real HTTP nodes — grow 2->3 and drain 3->2 under concurrent queries +
imports with byte-identical results and zero dropped writes, plus
kill-the-coordinator-mid-copy resume and abort-with-reversal."""

from __future__ import annotations

import json
import threading
import time

import pytest

from pilosa_tpu.cluster.topology import (
    Cluster,
    MixedEpochError,
    TopologyError,
    new_cluster,
)
from pilosa_tpu.net.client import ClientError, InternalClient
from pilosa_tpu.net.server import Server
from pilosa_tpu.ops.bitplane import SLICE_WIDTH
from pilosa_tpu.rebalance.deltalog import DeltaLog
from pilosa_tpu.rebalance.plan import compute_plan


# ---------------------------------------------------------------------------
# versioned topology epochs
# ---------------------------------------------------------------------------


class TestTopologyEpochs:
    def test_add_node_bumps_epoch(self):
        c = Cluster()
        e0 = c.epoch
        c.add_node("a:1")
        assert c.epoch == e0 + 1
        # idempotent re-add does not bump
        c.add_node("a:1")
        assert c.epoch == e0 + 1

    def test_add_node_rejected_during_transition(self):
        c = new_cluster(2)
        c.begin_transition(["host0:0", "host1:0", "host2:0"])
        with pytest.raises(TopologyError):
            c.add_node("host3:0")

    def test_reads_route_old_ring_until_flip(self):
        c = new_cluster(2)
        t = c.begin_transition(["host0:0", "host1:0", "host2:0"])
        moved = [
            s
            for s in range(16)
            if {n.host for n in c.new_ring_nodes("i", s)}
            != {n.host for n in c.partition_nodes(c.partition("i", s))}
        ]
        assert moved, "grow must move some slices"
        s = moved[0]
        before = [n.host for n in c.fragment_nodes("i", s)]
        assert "host2:0" not in before
        # writes already dual-target both rings
        assert {n.host for n in c.write_nodes("i", s)} >= set(before)
        assert c.flip_slice("i", s, t.epoch)
        after = [n.host for n in c.fragment_nodes("i", s)]
        assert after == [n.host for n in c.new_ring_nodes("i", s)]

    def test_commit_swaps_ring_and_bumps_epoch(self):
        c = new_cluster(2)
        t = c.begin_transition(["host0:0", "host1:0", "host2:0"])
        e = c.epoch
        c.commit_transition(t.epoch)
        assert c.hosts() == ["host0:0", "host1:0", "host2:0"]
        assert c.epoch > e
        assert c.transition is None

    def test_abort_refused_with_flipped_slices(self):
        c = new_cluster(2)
        t = c.begin_transition(["host0:0", "host1:0", "host2:0"])
        c.flip_slice("i", 0, t.epoch)
        with pytest.raises(TopologyError):
            c.abort_transition(t.epoch)
        c.unflip_slice("i", 0, t.epoch)
        c.abort_transition(t.epoch)
        assert c.transition is None
        assert c.hosts() == ["host0:0", "host1:0"]

    def test_snapshot_restore_roundtrip(self):
        c = new_cluster(2)
        t = c.begin_transition(["host0:0", "host1:0", "host2:0"])
        c.flip_slice("i", 3, t.epoch)
        snap = c.transition_snapshot()
        c2 = new_cluster(2)
        c2.restore_transition(snap)
        assert c2.transition_snapshot() == snap
        assert [n.host for n in c2.fragment_nodes("i", 3)] == [
            n.host for n in c.fragment_nodes("i", 3)
        ]

    def test_mixed_epoch_route_fails_loudly(self):
        from pilosa_tpu.core.holder import Holder
        from pilosa_tpu.exec.executor import Executor

        c = new_cluster(2)
        ex = Executor(Holder("/tmp/_nope"), host="host0:0", cluster=c)
        epoch0 = c.epoch
        ex._slices_by_node(list(c.nodes), "i", [0, 1], epoch=epoch0)  # fine
        c.add_node("host2:0")  # ring mutates mid-query
        with pytest.raises(MixedEpochError):
            ex._slices_by_node(list(c.nodes), "i", [0, 1], epoch=epoch0)

    def test_flip_invalidates_routing_cache(self):
        from pilosa_tpu.core.holder import Holder
        from pilosa_tpu.exec.executor import Executor

        c = new_cluster(2)
        t = c.begin_transition(["host0:0", "host1:0", "host2:0"])
        ex = Executor(Holder("/tmp/_nope"), host="host0:0", cluster=c)
        moved = next(
            s
            for s in range(16)
            if {n.host for n in c.new_ring_nodes("i", s)}
            != {n.host for n in c.fragment_nodes("i", s)}
        )
        m0 = ex._slices_by_node(c.route_nodes(), "i", [moved])
        owner0 = next(iter(m0))
        c.flip_slice("i", moved, t.epoch)
        m1 = ex._slices_by_node(c.route_nodes(), "i", [moved])
        owner1 = next(iter(m1))
        assert owner0 != owner1


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------


class TestPlan:
    def test_grow_plan_targets_only_new_host(self):
        c = new_cluster(2)
        c.begin_transition(["host0:0", "host1:0", "host2:0"])
        moves = compute_plan(c, {"i": 31})
        assert moves, "a grow must move slices"
        for m in moves:
            assert m.targets == ("host2:0",)
            assert m.releases == m.sources  # replica_n=1: old owner leaves
        # only slices whose owner set changed appear
        keys = {m.slice for m in moves}
        for s in range(32):
            old = {n.host for n in c.partition_nodes(c.partition("i", s))}
            new = {n.host for n in c.new_ring_nodes("i", s)}
            assert (s in keys) == (old != new)

    def test_drain_plan_is_inverse_of_grow(self):
        c3 = new_cluster(3)
        c3.begin_transition(["host0:0", "host1:0"])
        moves = compute_plan(c3, {"i": 31})
        assert moves
        for m in moves:
            assert m.sources == ("host2:0",) or "host2:0" in m.releases

    def test_no_transition_no_plan(self):
        assert compute_plan(new_cluster(3), {"i": 31}) == []


# ---------------------------------------------------------------------------
# delta log
# ---------------------------------------------------------------------------


class _Frag:
    def __init__(self, index="i", frame="f", view="standard", slice_i=0):
        self.index, self.frame, self.view, self.slice = index, frame, view, slice_i


class TestDeltaLog:
    def test_order_preserved_and_drain_resets(self):
        log = DeltaLog(cap=100)
        log.start("i", 0)
        f = _Frag()
        log.record(f, (1,), (10,), (), ())
        log.record(f, (), (), (1,), (10,))
        entries, overflowed = log.drain("i", 0)
        assert not overflowed
        assert [(e[2], e[4]) for e in entries] == [([1], []), ([], [1])]
        assert log.drain("i", 0) == ([], False)

    def test_inactive_slice_records_nothing(self):
        log = DeltaLog()
        log.record(_Frag(), (1,), (10,), (), ())
        assert log.drain("i", 0) == ([], False)

    def test_overflow_drops_and_flags(self):
        log = DeltaLog(cap=3)
        log.start("i", 0)
        f = _Frag()
        for k in range(5):
            log.record(f, (k,), (k,), (), ())
        entries, overflowed = log.drain("i", 0)
        assert overflowed and entries == []
        # drain resets the flag; logging resumes
        log.record(f, (9,), (9,), (), ())
        entries, overflowed = log.drain("i", 0)
        assert not overflowed and len(entries) == 1

    def test_requeue_preserves_head_order(self):
        log = DeltaLog(cap=100)
        log.start("i", 0)
        f = _Frag()
        log.record(f, (1,), (1,), (), ())
        entries, _ = log.drain("i", 0)
        log.record(f, (2,), (2,), (), ())
        log.requeue("i", 0, entries)
        drained, _ = log.drain("i", 0)
        assert [e[2] for e in drained] == [[1], [2]]

    def test_start_resets_stale_entries(self):
        log = DeltaLog(cap=100)
        log.start("i", 0)
        log.record(_Frag(), (1,), (1,), (), ())
        log.start("i", 0)  # fresh copy window
        assert log.drain("i", 0) == ([], False)


# ---------------------------------------------------------------------------
# end-to-end: live resize over real HTTP nodes
# ---------------------------------------------------------------------------

N_SLICES = 6


def _boot(tmp_path, name, host="127.0.0.1:0", ring=()):
    """One real node.  ``ring``: pre-configured host list — a node NOT
    in it boots as a JOINER (no self-registration)."""
    cluster = Cluster(replica_n=1)
    for h in ring:
        cluster.add_node(h)
    s = Server(
        data_dir=str(tmp_path / name),
        host=host,
        cluster=cluster,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        rebalance_release_delay_ms=0.0,
    )
    s.open()
    return s


def _wire(servers, hosts):
    for s in servers:
        for h in hosts:
            if s.cluster.node_by_host(h) is None:
                s.cluster.add_node(h)
        s.cluster.nodes.sort(key=lambda n: n.host)


def _schema(servers):
    for s in servers:
        s.holder.create_index_if_not_exists("i")
        s.holder.index("i").create_frame_if_not_exists("f")


def _seed(client, servers, row=1):
    """One bit per slice (deterministic corpus); returns expected count.
    Runs every node's max-slice polling tick afterwards (the fixtures
    disable the periodic loop)."""
    for sl in range(N_SLICES):
        client.execute_query(
            "i", f'SetBit(frame="f", rowID={row}, columnID={sl * SLICE_WIDTH + sl})'
        )
    for s in servers:
        s._tick_max_slices()
    return N_SLICES


def _count(client, row=1, retries=8):
    """Count with retry over the two loud-but-transient windows (the
    mixed-epoch guard at begin/commit, a breaker warming up)."""
    last = None
    for _ in range(retries):
        try:
            return client.execute_pql("i", f'Count(Bitmap(frame="f", rowID={row}))')
        except (ClientError, ConnectionError) as e:
            last = e
            time.sleep(0.1)
    raise last


def _bits(client, row=1, retries=8):
    from pilosa_tpu.net import codec

    last = None
    for _ in range(retries):
        try:
            rb = client.execute_pql("i", f'Bitmap(frame="f", rowID={row})')
            return codec.bitmap_to_json(rb)["bits"]
        except (ClientError, ConnectionError) as e:
            last = e
            time.sleep(0.1)
    raise last


def _debug_rebalance(host):
    client = InternalClient(host, timeout=10.0)
    status, data = client._request("GET", "/debug/rebalance")
    return json.loads(client._check(status, data))


def _resize(host, hosts):
    client = InternalClient(host, timeout=30.0)
    status, data = client._request(
        "POST", "/cluster/resize", body=json.dumps({"hosts": hosts}).encode()
    )
    return json.loads(client._check(status, data))


def _wait_complete(host, timeout=90.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        snap = _debug_rebalance(host)
        if not snap.get("running") and snap.get("transition") is None:
            return snap
        coord = snap.get("coordinator") or {}
        if not snap.get("running") and (coord.get("error") or snap.get("lastError")):
            raise AssertionError(
                f"migration stopped: {coord.get('error') or snap.get('lastError')}"
            )
        time.sleep(0.1)
    raise AssertionError(f"resize did not complete: {_debug_rebalance(host)}")


def _local_fragments(server, index="i"):
    idx = server.holder.index(index)
    if idx is None:
        return []
    return [
        (f.name, v.name, frag.slice)
        for f in idx.frames().values()
        for v in f.views().values()
        for frag in v.fragments()
    ]


class TestResizeE2E:
    def test_grow_2_to_3_under_concurrent_load(self, tmp_path):
        s0 = _boot(tmp_path, "n0")
        s1 = _boot(tmp_path, "n1")
        servers = [s0, s1]
        s2 = None
        stop = threading.Event()
        try:
            hosts2 = sorted([s0.host, s1.host])
            _wire(servers, hosts2)
            _schema(servers)
            c0 = InternalClient(s0.host, timeout=10.0)
            want = _seed(c0, servers)
            assert _count(c0) == want
            baseline_bits = _bits(c0)

            # The joining node: configured with the OLD ring, own host
            # not in it — it must NOT insert itself into placement.
            s2 = _boot(tmp_path, "n2", ring=hosts2)
            assert s2.cluster.node_by_host(s2.host) is None

            # Background load: readers assert byte-identical results on
            # every observation; a writer streams new bits (row 3) the
            # whole time — zero of them may be lost.
            errors: list[str] = []
            written: list[int] = []

            def reader():
                # A WRONG result is an immediate failure; a transient
                # transport error under a loaded CI machine is retried
                # (consecutive-failure bound, not one-strike).
                misses = 0
                while not stop.is_set():
                    try:
                        if _bits(c0) != baseline_bits:
                            errors.append("reader observed wrong bits")
                            return
                        misses = 0
                    except Exception as e:  # noqa: BLE001
                        misses += 1
                        if misses >= 5:
                            errors.append(f"reader: {e}")
                            return
                    time.sleep(0.02)

            def writer():
                cw = InternalClient(s0.host, timeout=10.0)
                k = 0
                while not stop.is_set():
                    col = (k % N_SLICES) * SLICE_WIDTH + 100 + k // N_SLICES
                    # Writes are briefly blocked during migration
                    # critical phases; on a loaded machine those
                    # phases stretch, so the retry budget must be
                    # seconds wide, not the happy-path 0.5 s.
                    give_up = time.monotonic() + 30.0
                    while True:
                        try:
                            cw.execute_query(
                                "i",
                                f'SetBit(frame="f", rowID=3, columnID={col})',
                            )
                            written.append(col)
                            break
                        except (ClientError, ConnectionError):
                            if stop.is_set():
                                # unacked in-flight write at shutdown:
                                # not in the oracle, not an error
                                return
                            if time.monotonic() > give_up:
                                errors.append(f"writer gave up on col {col}")
                                return
                            time.sleep(0.1)
                    k += 1
                    time.sleep(0.01)

            threads = [
                threading.Thread(target=reader, daemon=True),
                threading.Thread(target=writer, daemon=True),
            ]
            for t in threads:
                t.start()
            time.sleep(0.2)

            hosts3 = sorted(hosts2 + [s2.host])
            _resize(s0.host, hosts3)
            _wait_complete(s0.host)
            time.sleep(0.3)  # let in-flight writes settle
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            assert not errors, errors

            # Ring committed everywhere.
            for s in [s0, s1, s2]:
                assert s.cluster.hosts() == hosts3, s.host
                assert s.cluster.transition is None

            # Byte-identical results from every coordinator, including
            # the joined node.
            for s in [s0, s1, s2]:
                cc = InternalClient(s.host, timeout=10.0)
                assert _count(cc) == want, s.host
                assert _bits(cc) == baseline_bits, s.host

            # Zero dropped writes: every bit the writer confirmed is
            # countable after the cutover.
            assert written, "writer made no progress during migration"
            expect3 = len(set(written))
            for s in [s0, s1, s2]:
                cc = InternalClient(s.host, timeout=10.0)
                assert _count(cc, row=3) == expect3, s.host

            # The joined node actually owns slices; the sources
            # RELEASED them (fragments gone => HBM/disk returned).
            owned2 = {
                sl
                for sl in range(N_SLICES)
                if s2.cluster.fragment_nodes("i", sl)[0].host == s2.host
            }
            assert owned2, "grow moved no slices to the new node"
            got2 = {sl for (_, _, sl) in _local_fragments(s2)}
            assert owned2 <= got2
            for s in (s0, s1):
                stale = {
                    sl
                    for (_, _, sl) in _local_fragments(s)
                    if sl in owned2
                }
                assert not stale, f"{s.host} kept released slices {stale}"

            # Migration observability surfaced.
            snap = _debug_rebalance(s0.host)
            assert snap["transition"] is None and not snap["running"]
        finally:
            stop.set()
            for s in servers + ([s2] if s2 else []):
                s.close()

    def test_grow_mesh_sharded_slices_land_on_target_shard(self, tmp_path):
        """ISSUE 12: rebalance composed with the mesh data plane.  Every
        node runs the >1-device virtual mesh (conftest forces 8 CPU
        devices), so migrated slices must re-materialize on the TARGET
        node's correct mesh shard (slice mod n_devices), with results
        byte-identical across every coordinator and zero lost writes
        from a writer racing the migration."""
        from pilosa_tpu.ops import bitplane as bp
        from pilosa_tpu.parallel import mesh as pmesh

        assert pmesh.default_slices_mesh() is not None, (
            "the mesh data plane must be engaged on every node"
        )
        s0 = _boot(tmp_path, "n0")
        s1 = _boot(tmp_path, "n1")
        servers = [s0, s1]
        s2 = None
        stop = threading.Event()
        try:
            hosts2 = sorted([s0.host, s1.host])
            _wire(servers, hosts2)
            _schema(servers)
            c0 = InternalClient(s0.host, timeout=10.0)
            want = _seed(c0, servers)
            assert _count(c0) == want
            baseline_bits = _bits(c0)

            s2 = _boot(tmp_path, "n2", ring=hosts2)

            errors: list[str] = []
            written: list[int] = []

            def writer():
                cw = InternalClient(s0.host, timeout=10.0)
                k = 0
                while not stop.is_set():
                    col = (k % N_SLICES) * SLICE_WIDTH + 200 + k // N_SLICES
                    give_up = time.monotonic() + 30.0
                    while True:
                        try:
                            cw.execute_query(
                                "i",
                                f'SetBit(frame="f", rowID=5, columnID={col})',
                            )
                            written.append(col)
                            break
                        except (ClientError, ConnectionError):
                            if stop.is_set():
                                return
                            if time.monotonic() > give_up:
                                errors.append(f"writer gave up on col {col}")
                                return
                            time.sleep(0.1)
                    k += 1
                    time.sleep(0.01)

            t = threading.Thread(target=writer, daemon=True)
            t.start()
            time.sleep(0.1)

            hosts3 = sorted(hosts2 + [s2.host])
            _resize(s0.host, hosts3)
            _wait_complete(s0.host)
            time.sleep(0.3)
            stop.set()
            t.join(timeout=10.0)
            assert not errors, errors

            # Byte-identical results through every coordinator —
            # including the joined node, whose local map leg runs the
            # mesh-sharded batch path over its migrated slices.
            for s in [s0, s1, s2]:
                cc = InternalClient(s.host, timeout=10.0)
                assert _count(cc) == want, s.host
                assert _bits(cc) == baseline_bits, s.host
            assert written, "writer made no progress during migration"
            expect5 = len(set(written))
            for s in [s0, s1, s2]:
                cc = InternalClient(s.host, timeout=10.0)
                assert _count(cc, row=5) == expect5, s.host

            # Migrated slices landed on the target — and their restored
            # HBM mirrors sit on the slice's OWNING mesh shard (the
            # ?stage=true restore lane hands them to the prefetcher,
            # which places via home_device).
            owned2 = {
                sl
                for sl in range(N_SLICES)
                if s2.cluster.fragment_nodes("i", sl)[0].host == s2.host
            }
            assert owned2, "grow moved no slices to the new node"
            view = s2.holder.index("i").frame("f").view("standard")
            for sl in owned2:
                frag = view.fragment(sl)
                assert frag is not None, f"slice {sl} missing on target"
                # The restore lane stages asynchronously; a direct
                # device_plane() is placement-deterministic either way.
                mirror = frag.device_plane()
                (dev,) = mirror.devices()
                assert dev == bp.home_device(sl), (
                    f"slice {sl} on {dev}, owning shard "
                    f"{bp.home_device(sl)}"
                )
            # Zero lost writes is already asserted via expect5 above;
            # finally, the target's shard spread is real (mesh engaged,
            # not everything on device 0) whenever it owns >1 slice
            # with distinct home shards.
            homes = {str(bp.home_device(sl)) for sl in owned2}
            assert len(homes) == len(
                {sl % bp.mesh_device_count() for sl in owned2}
            )
        finally:
            stop.set()
            for s in servers + ([s2] if s2 else []):
                s.close()

    def test_drain_3_to_2_releases_and_preserves_results(self, tmp_path):
        servers = [_boot(tmp_path, f"n{i}") for i in range(3)]
        try:
            hosts3 = sorted(s.host for s in servers)
            _wire(servers, hosts3)
            _schema(servers)
            c0 = InternalClient(servers[0].host, timeout=10.0)
            want = _seed(c0, servers)
            baseline = _bits(c0)

            victim = max(servers, key=lambda s: s.host)
            keep = sorted(h for h in hosts3 if h != victim.host)
            coord = next(s for s in servers if s.host == keep[0])
            _resize(coord.host, keep)
            _wait_complete(coord.host)

            for s in servers:
                assert s.cluster.hosts() == keep, s.host
            for h in keep:
                cc = InternalClient(h, timeout=10.0)
                assert _count(cc) == want
                assert _bits(cc) == baseline
            # The drained node holds NOTHING afterwards.
            assert _local_fragments(victim) == []
        finally:
            for s in servers:
                s.close()

    def test_kill_coordinator_mid_copy_then_resume(self, tmp_path):
        s0 = _boot(tmp_path, "n0")
        s1 = _boot(tmp_path, "n1")
        s2 = None
        try:
            hosts2 = sorted([s0.host, s1.host])
            _wire([s0, s1], hosts2)
            _schema([s0, s1])
            c0 = InternalClient(s0.host, timeout=10.0)
            want = _seed(c0, [s0, s1])
            baseline = _bits(c0)

            s2 = _boot(tmp_path, "n2", ring=hosts2)
            hosts3 = sorted(hosts2 + [s2.host])

            # Slow the coordinator down so the kill lands mid-plan.
            s0.rebalance.step_delay_s = 0.5
            _resize(s0.host, hosts3)
            deadline = time.time() + 60
            while time.time() < deadline:
                snap = _debug_rebalance(s0.host)
                done = (snap.get("coordinator") or {}).get("sliceStates", {}).get(
                    "done", 0
                )
                if done >= 1:
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("no slice completed before kill")

            # KILL the coordinator mid-migration.
            s0_host, s0_dir = s0.host, s0.data_dir
            s0.close()

            # Both rings remain valid on the surviving peers: a flipped
            # slice answers from its NEW owner.
            peer_snap = _debug_rebalance(s1.host)
            assert peer_snap["transition"] is not None
            moved = peer_snap["transition"]["moved"]
            assert moved, "peer lost the flipped-slice set"
            # A grow can also move slices between EXISTING nodes; probe
            # a flipped slice whose NEW owner survived the kill.
            probe = None
            for idx_name, moved_slice in moved:
                owner = s1.cluster.fragment_nodes(idx_name, int(moved_slice))[0]
                if owner.host != s0_host:
                    probe = (idx_name, int(moved_slice))
                    break
            if probe is not None:
                c1 = InternalClient(s1.host, timeout=10.0)
                got = c1.execute_query(
                    probe[0],
                    'Count(Bitmap(frame="f", rowID=1))',
                    slices=[probe[1]],
                )
                assert got[0] == 1  # the seeded bit of that slice

            # RESTART the coordinator on its old identity: the
            # persisted transition restores at boot...
            s0 = _boot(tmp_path, "n0", host=s0_host, ring=hosts2)
            assert s0.data_dir == s0_dir
            assert s0.cluster.transition is not None
            done_before = len(
                (
                    (self_state := _debug_rebalance(s0.host)).get("coordinator")
                    or {}
                ).get("slices", {})
            )
            assert done_before >= 1, self_state

            # ...and a re-issued resize picks up from the per-slice
            # migration state and completes.
            _resize(s0.host, hosts3)
            _wait_complete(s0.host)

            for s in [s0, s1, s2]:
                assert s.cluster.hosts() == hosts3
                cc = InternalClient(s.host, timeout=10.0)
                assert _count(cc) == want
                assert _bits(cc) == baseline
        finally:
            for s in (s0, s1, s2):
                if s is not None:
                    s.close()

    def test_abort_reverses_flipped_slices(self, tmp_path):
        s0 = _boot(tmp_path, "n0")
        s1 = _boot(tmp_path, "n1")
        s2 = None
        try:
            hosts2 = sorted([s0.host, s1.host])
            _wire([s0, s1], hosts2)
            _schema([s0, s1])
            c0 = InternalClient(s0.host, timeout=10.0)
            want = _seed(c0, [s0, s1])
            baseline = _bits(c0)

            s2 = _boot(tmp_path, "n2", ring=hosts2)
            hosts3 = sorted(hosts2 + [s2.host])
            s0.rebalance.step_delay_s = 5.0  # pause after each slice
            _resize(s0.host, hosts3)
            deadline = time.time() + 60
            while time.time() < deadline:
                snap = _debug_rebalance(s0.host)
                if (snap.get("coordinator") or {}).get("sliceStates", {}).get(
                    "done", 0
                ) >= 1:
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("no slice completed before abort")

            client = InternalClient(s0.host, timeout=120.0)
            status, data = client._request("POST", "/cluster/resize/abort")
            client._check(status, data)

            # Old ring restored everywhere, results intact, the
            # would-be joiner holds nothing.
            for s in [s0, s1, s2]:
                assert s.cluster.transition is None, s.host
            assert s0.cluster.hosts() == hosts2
            assert s1.cluster.hosts() == hosts2
            for s in (s0, s1):
                cc = InternalClient(s.host, timeout=10.0)
                assert _count(cc) == want
                assert _bits(cc) == baseline
            assert _local_fragments(s2) == []
        finally:
            for s in (s0, s1, s2):
                if s is not None:
                    s.close()
