"""Test fixtures: force JAX onto a virtual 8-device CPU mesh.

The distributed logic must be testable without a TPU pod (SURVEY.md §4
implication), so every test runs on the CPU backend with 8 virtual
devices; the driver separately dry-run-compiles the multi-chip path and
benches on real TPU hardware.
"""

import os

# Force the CPU backend even when the container routes JAX at a TPU by
# default (JAX_PLATFORMS=axon + a sitecustomize that registers the tunnel
# plugin whenever PALLAS_AXON_POOL_IPS is set).  Tests must never touch
# the real chip: clearing the pool IPs prevents plugin registration in
# pytest worker processes, and JAX_PLATFORMS=cpu selects the host backend.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The container's sitecustomize imports jax at interpreter startup (to
# register the TPU-tunnel PJRT plugin), which latches JAX_PLATFORMS=axon
# before this file runs — so updating the env alone is not enough: update
# the live config too, before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_sessionfinish(session, exitstatus):
    """PILOSA_LOCK_CHECK=1: after the suite, assert every lock
    acquisition order observed at runtime is consistent with the static
    lock graph (pilosa_tpu/analyze) — the analyzer is proven against
    reality on every instrumented run, not just committed."""
    if not os.environ.get("PILOSA_LOCK_CHECK"):
        return
    from pilosa_tpu.analyze import runtime as lock_check

    problems = lock_check.verify()
    rep = session.config.pluginmanager.get_plugin("terminalreporter")
    lines = [lock_check.report().splitlines()[0]]
    if problems:
        lines.append("lock-check: STATIC/RUNTIME DISAGREEMENT")
        lines.extend("  " + p for p in problems)
        session.exitstatus = 1
    else:
        lines.append("lock-check: runtime acquisition order consistent "
                     "with the static lock graph")
    for ln in lines:
        if rep is not None:
            rep.write_line(ln)
        else:
            print(ln)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def positions_to_words(positions, n_words=1024):
    """Pack bit positions into uint64 words — shared by the roaring,
    native-parity, and property test suites."""
    w = np.zeros(n_words, dtype=np.uint64)
    for p in positions:
        w[p // 64] |= np.uint64(1) << np.uint64(p % 64)
    return w


def free_udp_port() -> int:
    """Reserve-and-release a local UDP port — shared by the gossip unit
    tests and the multi-node cluster tests."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
