"""Compressed device planes (PR 19): container codecs, format-aware
membership/expansion kernels, the anchored position-domain count route,
and the program-cache bounds that keep its jit keys pure geometry.

The acceptance bar is byte-identity everywhere: every container format
must answer exactly like the dense path and a numpy set oracle across
the full PQL storm, through rows that straddle the format thresholds
and rows mutated across formats by set/clear writes.
"""

import numpy as np
import pytest

import pilosa_tpu.core.fragment as fr
from pilosa_tpu.cluster.topology import new_cluster
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import Executor, plan
from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.pql.parser import parse_string

SW = bp.SLICE_WIDTH


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def sparse_tier(monkeypatch):
    """Force every fragment into the sparse tier (dense budget 0), the
    placement where compressed device formats engage."""
    orig = fr.Fragment.__init__

    def zero_budget(self, *a, **kw):
        kw.setdefault("dense_row_budget", 0)
        orig(self, *a, **kw)

    monkeypatch.setattr(fr.Fragment, "__init__", zero_budget)


@pytest.fixture(autouse=True)
def auto_format():
    """Every test starts from the default policy and restores it."""
    bp.configure_plane_format(
        mode="auto", sparse_max_bytes=65536, rle_max_bytes=65536
    )
    yield
    bp.configure_plane_format(
        mode="auto", sparse_max_bytes=65536, rle_max_bytes=65536
    )


def _clustered(rng, card, runs=8):
    run_len = max(1, card // runs)
    cols = set()
    for st in rng.choice(SW - run_len, size=runs, replace=False):
        cols.update(range(int(st), int(st) + run_len))
    return cols


def _scattered(rng, card):
    return {int(p) for p in rng.choice(SW, size=card, replace=False)}


# ---------------------------------------------------------------------------
# codec roundtrips
# ---------------------------------------------------------------------------


def test_encode_decode_roundtrip_randomized(rng):
    """encode_row -> decode_payload is the identity for every format the
    selector picks, across the density spectrum."""
    cases = [
        np.array([], dtype=np.uint32),
        np.array([0], dtype=np.uint32),
        np.array([SW - 1], dtype=np.uint32),
        np.arange(SW, dtype=np.uint32),  # full slice: one run
    ]
    for card in (3, 77, 1000, 10_000, 60_000, 200_000):
        cases.append(
            np.array(sorted(_scattered(rng, card)), dtype=np.uint32)
        )
        cases.append(
            np.array(sorted(_clustered(rng, card)), dtype=np.uint32)
        )
    seen_fmts = set()
    for offs in cases:
        fmt, payload, nbytes = bp.encode_row(offs)
        seen_fmts.add(fmt)
        # decode_payload is the host oracle: payload -> dense row words
        back = bp.np_row_to_columns(bp.decode_payload(fmt, payload))
        np.testing.assert_array_equal(back, offs.astype(np.uint64))
        assert nbytes == payload.nbytes
    assert seen_fmts == {bp.FMT_DENSE, bp.FMT_SPARSE, bp.FMT_RLE}


def test_forced_dense_mode_disables_compression(rng):
    bp.configure_plane_format(mode="dense")
    offs = np.array(sorted(_clustered(rng, 500)), dtype=np.uint32)
    fmt, payload, nbytes = bp.encode_row(offs)
    assert fmt == bp.FMT_DENSE
    assert nbytes == bp.WORDS_PER_SLICE * 4


def test_threshold_straddle_rows(rng):
    """Rows straddling the sparse-vs-dense byte threshold flip format
    exactly at the configured ceiling."""
    # 4 * pow2_bucket(card) must be < 128 KiB AND <= SPARSE_MAX_BYTES
    # for the position format; a scattered row of 16384 positions costs
    # exactly 64 KiB, one of 16385 rounds to 128 KiB and stays dense.
    under = np.array(sorted(_scattered(rng, 16384)), dtype=np.uint32)
    fmt_u, _, nb_u = bp.encode_row(under)
    assert (fmt_u, nb_u) == (bp.FMT_SPARSE, 65536)
    over = np.array(sorted(_scattered(rng, 16385)), dtype=np.uint32)
    fmt_o, _, nb_o = bp.encode_row(over)
    assert fmt_o == bp.FMT_DENSE
    # Tightening the ceiling reclassifies the under row too.
    bp.configure_plane_format(sparse_max_bytes=32768)
    fmt_t, _, _ = bp.encode_row(under)
    assert fmt_t == bp.FMT_DENSE


def test_rle_ceiling_falls_back(rng):
    """Past rle-max-bytes, a clustered row degrades to sparse/dense
    instead of an oversized run payload."""
    cols = np.array(sorted(_clustered(rng, 4000, runs=1000)), dtype=np.uint32)
    fmt, _, _ = bp.encode_row(cols)
    assert fmt == bp.FMT_RLE
    bp.configure_plane_format(rle_max_bytes=1024)
    fmt2, _, _ = bp.encode_row(cols)
    assert fmt2 != bp.FMT_RLE


# ---------------------------------------------------------------------------
# membership + expansion vs numpy
# ---------------------------------------------------------------------------


def test_membership_kernels_vs_numpy(rng):
    import jax.numpy as jnp

    for maker, card in (
        (_scattered, 900),
        (_clustered, 3000),
        (_scattered, 31),
    ):
        cols = maker(rng, card)
        offs = np.array(sorted(cols), dtype=np.uint32)
        probe = np.array(
            sorted(
                set(rng.choice(SW, size=512).tolist())
                | set(list(cols)[:64])
            ),
            dtype=np.uint32,
        )
        want = np.array([int(p) in cols for p in probe])
        dense = np.zeros(bp.WORDS_PER_SLICE, dtype=np.uint32)
        for p in offs:
            dense[p >> 5] |= np.uint32(1) << np.uint32(p & 31)
        got_d = np.asarray(
            bp.membership_dense(jnp.asarray(dense), jnp.asarray(probe))
        )
        np.testing.assert_array_equal(got_d, want)
        for fmt, payload, _nb in (
            bp.encode_row(offs),
        ):
            if fmt == bp.FMT_SPARSE:
                got = np.asarray(
                    bp.membership_sparse(
                        jnp.asarray(payload), jnp.asarray(probe)
                    )
                )
            elif fmt == bp.FMT_RLE:
                got = np.asarray(
                    bp.membership_rle(
                        jnp.asarray(payload), jnp.asarray(probe)
                    )
                )
            else:
                continue
            np.testing.assert_array_equal(got, want)


def test_expand_payload_vs_numpy(rng):
    cases = [
        np.array([], dtype=np.uint32),
        np.array([0, 31, 32, SW - 1], dtype=np.uint32),
        np.arange(SW, dtype=np.uint32),  # full slice
        np.array(sorted(_scattered(rng, 5000)), dtype=np.uint32),
        np.array(sorted(_clustered(rng, 5000)), dtype=np.uint32),
    ]
    for offs in cases:
        dense = np.zeros(bp.WORDS_PER_SLICE, dtype=np.uint32)
        for p in offs:
            dense[p >> 5] |= np.uint32(1) << np.uint32(p & 31)
        fmt, payload, _nb = bp.encode_row(offs)
        got = np.asarray(bp.expand_payload(fmt, payload))
        np.testing.assert_array_equal(got, dense)


# ---------------------------------------------------------------------------
# anchored count through the executor vs the host oracle
# ---------------------------------------------------------------------------


def _corpus(holder, rng, n_rows=6, slices=2, card=2000):
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists("f")
    f.set_options(range_enabled=True)
    if f.bsi_field("v") is None:
        f.create_field("v", 0, 500)
    oracle = {}
    rows_in, cols_in = [], []
    for row in range(n_rows):
        cols = set()
        for s in range(slices):
            part = (
                _scattered(rng, card) if row % 3 == 1
                else _clustered(rng, card)
            )
            cols.update(p + s * SW for p in part)
        oracle[row] = cols
        for c in sorted(cols):
            rows_in.append(row)
            cols_in.append(c)
    f.import_bulk(rows_in, cols_in)
    vcols = sorted(oracle[0])[:300]
    f.import_value("v", vcols, [c % 500 for c in vcols])
    return f, oracle


def test_anchored_count_matches_oracle(sparse_tier, holder, rng):
    _f, oracle = _corpus(holder, rng)
    c = new_cluster(1)
    ex = Executor(holder, host=c.nodes[0].host, cluster=c)
    plan.clear_program_caches()
    for a in range(6):
        b = (a + 1) % 6
        d = (a + 2) % 6
        for pql, want in (
            (
                f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
                f" Bitmap(rowID={b}, frame=f)))",
                len(oracle[a] & oracle[b]),
            ),
            (
                f"Count(Difference(Bitmap(rowID={a}, frame=f),"
                f" Bitmap(rowID={b}, frame=f)))",
                len(oracle[a] - oracle[b]),
            ),
            (
                f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
                f" Union(Bitmap(rowID={b}, frame=f),"
                f" Bitmap(rowID={d}, frame=f))))",
                len(oracle[a] & (oracle[b] | oracle[d])),
            ),
        ):
            (got,) = ex.execute("i", parse_string(pql), None, None)
            assert int(got) == want, pql
    # the route actually engaged (not the word-domain fallback)
    assert plan.program_cache_stats().get("plan.anchored", 0) > 0


def test_absent_row_and_empty_anchor(sparse_tier, holder, rng):
    _f, oracle = _corpus(holder, rng, n_rows=2, slices=1)
    c = new_cluster(1)
    ex = Executor(holder, host=c.nodes[0].host, cluster=c)
    (got,) = ex.execute(
        "i",
        parse_string(
            "Count(Intersect(Bitmap(rowID=0, frame=f),"
            " Bitmap(rowID=77, frame=f)))"
        ),
        None,
        None,
    )
    assert int(got) == 0
    (got,) = ex.execute(
        "i",
        parse_string(
            "Count(Intersect(Bitmap(rowID=77, frame=f),"
            " Bitmap(rowID=0, frame=f)))"
        ),
        None,
        None,
    )
    assert int(got) == 0


# ---------------------------------------------------------------------------
# full PQL storm: auto formats vs forced dense vs host oracle
# ---------------------------------------------------------------------------


def _storm(ex, n_rows):
    out = []
    for a in range(n_rows):
        b = (a + 1) % n_rows
        for pql in (
            f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
            f" Bitmap(rowID={b}, frame=f)))",
            f"Count(Union(Bitmap(rowID={a}, frame=f),"
            f" Bitmap(rowID={b}, frame=f)))",
            f"Count(Difference(Bitmap(rowID={a}, frame=f),"
            f" Bitmap(rowID={b}, frame=f)))",
        ):
            (r,) = ex.execute("i", parse_string(pql), None, None)
            out.append(int(r))
    (bm,) = ex.execute("i", parse_string("Bitmap(rowID=0, frame=f)"), None, None)
    out.append(tuple(bm.bits()))
    (tn,) = ex.execute("i", parse_string("TopN(frame=f, n=4)"), None, None)
    out.append(tuple((p.id, p.count) for p in tn))
    (rg,) = ex.execute(
        "i", parse_string("Range(frame=f, v > 250)"), None, None
    )
    out.append(tuple(rg.bits()))
    (sm,) = ex.execute("i", parse_string("Sum(frame=f, field=v)"), None, None)
    out.append((int(sm.value), int(sm.count)))
    return out


def test_pql_storm_auto_vs_dense_byte_identical(sparse_tier, holder, rng):
    """The whole storm — Count over fold trees, Bitmap, TopN, Range,
    Sum — over compressed planes must match the forced-dense arm bit
    for bit (which the rest of the suite pins to the host oracle).
    Runs on the virtual 8-device mesh (conftest), so the mesh-sharded
    assembly path pages compressed rows through expand_payload."""
    _f, oracle = _corpus(holder, rng)
    c = new_cluster(1)
    ex = Executor(holder, host=c.nodes[0].host, cluster=c)
    plan.clear_program_caches()
    auto_res = _storm(ex, 6)
    bp.configure_plane_format(mode="dense")
    plan.clear_program_caches()
    dense_res = _storm(ex, 6)
    assert auto_res == dense_res
    # spot-check the oracle directly too
    assert auto_res[0] == len(oracle[0] & oracle[1])
    assert auto_res[-4] == tuple(sorted(oracle[0]))


def test_storm_coalesced_byte_identical(sparse_tier, holder, rng):
    """Counts routed through the coalescer over compressed planes match
    the direct path."""
    from pilosa_tpu.exec.coalesce import CoalesceScheduler

    _f, oracle = _corpus(holder, rng, n_rows=4, slices=1)
    c = new_cluster(1)
    plain = Executor(holder, host=c.nodes[0].host, cluster=c)
    want = _storm(plain, 4)
    plain.close()
    co = CoalesceScheduler(max_wait_us=0)
    ex = Executor(holder, host=c.nodes[0].host, cluster=c, coalescer=co)
    try:
        assert _storm(ex, 4) == want
    finally:
        ex.close()
        co.close()


# ---------------------------------------------------------------------------
# cross-format mutation: set/clear moves rows between formats
# ---------------------------------------------------------------------------


def test_row_mutates_across_formats(sparse_tier, holder, rng):
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists("f")
    frag_oracle = set()

    def check(expect_fmt=None):
        frag = holder.fragment("i", "f", "standard", 0)
        fmt, payload, _nb, card = frag.host_payload(7)
        assert card == len(frag_oracle)
        np.testing.assert_array_equal(
            bp.np_row_to_columns(bp.decode_payload(fmt, payload)),
            np.array(sorted(frag_oracle), dtype=np.uint64),
        )
        if expect_fmt is not None:
            assert fmt == expect_fmt

    # clustered run -> RLE
    for col in range(1000, 3000):
        f.set_bit("standard", 7, col)
        frag_oracle.add(col)
    check(bp.FMT_RLE)
    # scatter bits everywhere -> too many runs, packed positions win
    for col in rng.choice(SW, size=3000, replace=False):
        f.set_bit("standard", 7, int(col))
        frag_oracle.add(int(col))
    check()
    frag = holder.fragment("i", "f", "standard", 0)
    fmt_now, *_ = frag.host_payload(7)
    assert fmt_now in (bp.FMT_SPARSE, bp.FMT_RLE)
    # bulk-scatter past the sparse/rle byte ceilings -> dense wins
    more = [int(p) for p in rng.choice(SW, size=17_000, replace=False)]
    f.import_bulk([7] * len(more), more)
    frag_oracle.update(more)
    check(bp.FMT_DENSE)
    # clear back down to a handful -> compressed again
    for col in sorted(frag_oracle)[10:]:
        f.clear_bit("standard", 7, col)
    frag_oracle = set(sorted(frag_oracle)[:10])
    check(bp.FMT_SPARSE)


def test_dense_tier_rows_stay_dense_format(holder, rng):
    """Rows inside the dense budget serve FMT_DENSE payloads (the
    PR-18 scatter path applies deltas into exactly these rows)."""
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists("f")
    f.set_bit("standard", 1, 5)
    frag = holder.fragment("i", "f", "standard", 0)
    fmt, payload, nbytes, card = frag.host_payload(1)
    assert fmt == bp.FMT_DENSE
    assert nbytes == bp.WORDS_PER_SLICE * 4
    assert card == 1


# ---------------------------------------------------------------------------
# program-cache bounds under format diversity
# ---------------------------------------------------------------------------


def test_format_diversity_respects_cache_bound(sparse_tier, holder, rng):
    """Churning anchored queries across container formats, payload
    buckets, and expression shapes must keep every program-cache family
    inside its advertised ceiling."""
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists("f")
    oracle = {}
    rows_in, cols_in = [], []
    # format diversity: rle / sparse / bigger payload buckets
    for row, (maker, card) in enumerate(
        [
            (_clustered, 200),
            (_scattered, 150),
            (_clustered, 5000),
            (_scattered, 4000),
            (_clustered, 20_000),
            (_scattered, 151),
        ]
    ):
        cols = maker(rng, card)
        oracle[row] = cols
        for c in sorted(cols):
            rows_in.append(row)
            cols_in.append(c)
    f.import_bulk(rows_in, cols_in)
    c = new_cluster(1)
    ex = Executor(holder, host=c.nodes[0].host, cluster=c)
    plan.clear_program_caches()
    for a in range(6):
        for b in range(6):
            if a == b:
                continue
            (got,) = ex.execute(
                "i",
                parse_string(
                    f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
                    f" Bitmap(rowID={b}, frame=f)))"
                ),
                None,
                None,
            )
            assert int(got) == len(oracle[a] & oracle[b])
    stats = plan.program_cache_stats()
    bounds = plan.program_cache_bounds()
    assert stats.get("plan.anchored", 0) > 0
    for fam in ("plan.anchored", "bitplane.expand"):
        assert stats.get(fam, 0) <= bounds[fam], (fam, stats, bounds)
