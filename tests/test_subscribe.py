"""Standing queries (pilosa_tpu/subscribe): registry compilation, the
per-fragment listener lifecycle, incremental delta evaluation against
the hosteval oracle (randomized storm), overflow re-basing, delivery
semantics (at-least-once, version-monotonic), and a subscription
surviving a live 2->3 resize."""

from __future__ import annotations

import json
import random
import time

import pytest

from pilosa_tpu.cluster.topology import Cluster
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.net.client import ClientError, InternalClient
from pilosa_tpu.net.server import Server
from pilosa_tpu.ops.bitplane import SLICE_WIDTH
from pilosa_tpu.pql.parser import Query, parse_string
from pilosa_tpu.rebalance.deltalog import DeltaLog
from pilosa_tpu.subscribe import registry as reg
from pilosa_tpu.subscribe.registry import SubscribeError


# ---------------------------------------------------------------------------
# registry compilation
# ---------------------------------------------------------------------------


def _compile(pql: str):
    q = parse_string(pql)
    return reg.compile_subscription(q.calls[0])


class TestRegistry:
    def test_count_bitmap(self):
        kind, inner, tree, keys, force = _compile(
            "Subscribe(Count(Bitmap(rowID=3, frame=f)))"
        )
        assert kind == reg.KIND_COUNT
        assert inner.name == "Count"
        assert tree.name == "Bitmap"
        assert keys == {("f", 3)}
        assert not force

    def test_bare_tree_wrapped_in_count(self):
        kind, inner, tree, keys, _ = _compile(
            "Subscribe(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=g)))"
        )
        assert kind == reg.KIND_COUNT
        assert inner.name == "Count"
        assert keys == {("f", 1), ("g", 2)}

    def test_topn_forces_pull(self):
        kind, inner, tree, keys, force = _compile("Subscribe(TopN(frame=f, n=5))")
        assert kind == reg.KIND_TOPN
        assert tree is None
        assert keys == {("f", None)}
        assert force

    def test_range_is_frame_wildcard(self):
        _, _, _, keys, _ = _compile("Subscribe(Count(Range(frame=f, v > 10)))")
        assert keys == {("f", None)}

    def test_rejects_bad_shapes(self):
        for pql in (
            "Subscribe()",
            "Subscribe(Count(Bitmap(rowID=1)), Count(Bitmap(rowID=2)))",
            "Subscribe(SetBit(rowID=1, frame=f, columnID=2))",
            "Subscribe(Sum(frame=f, field=v))",
        ):
            with pytest.raises(SubscribeError):
                _compile(pql)


# ---------------------------------------------------------------------------
# fragment listener lifecycle (regression: a closed fragment must hold
# zero registered listeners)
# ---------------------------------------------------------------------------


class TestFragmentListeners:
    def _frag(self, tmp_path):
        return Fragment(
            path=str(tmp_path / "frag"), index="i", frame="f",
            view="standard", slice_i=0,
        )

    def test_close_clears_listeners(self, tmp_path):
        frag = self._frag(tmp_path)
        calls = []
        frag.add_write_listener(lambda *a, **k: calls.append(a))
        frag.open()
        frag.set_bit(1, 2)
        assert calls, "listener must fire on a point write"
        assert frag.write_listener_count() == 1
        frag.close()
        assert frag.write_listener_count() == 0

    def test_retire_clears_listeners(self, tmp_path):
        frag = self._frag(tmp_path)
        frag.open()
        frag.add_write_listener(lambda *a, **k: None)
        assert frag.write_listener_count() == 1
        frag.mark_retired()
        assert frag.write_listener_count() == 0
        frag.close()

    def test_add_remove_dedupe(self, tmp_path):
        frag = self._frag(tmp_path)
        fn = lambda *a, **k: None  # noqa: E731
        frag.add_write_listener(fn)
        frag.add_write_listener(fn)
        assert frag.write_listener_count() == 1
        frag.remove_write_listener(fn)
        assert frag.write_listener_count() == 0

    def test_point_writes_are_exact_imports_are_not(self, tmp_path):
        frag = self._frag(tmp_path)
        frag.open()
        seen = []
        frag.add_write_listener(
            lambda f, sr, sc, cr, cc, exact: seen.append(
                (list(sr), list(cr), exact)
            )
        )
        frag.set_bit(1, 2)
        frag.set_bit(1, 2)  # no-op: must NOT notify
        frag.clear_bit(1, 2)
        frag.import_bulk([1, 1], [3, 3])  # raw request, dupes included
        frag.close()
        assert seen[0] == ([1], [], True)
        assert seen[1] == ([], [1], True)
        assert seen[2] == ([1, 1], [], False)
        assert len(seen) == 3


# ---------------------------------------------------------------------------
# per-slice delta-log overflow observability
# ---------------------------------------------------------------------------


class _Frag:
    def __init__(self, index="i", frame="f", view="standard", slice_i=0):
        self.index, self.frame, self.view, self.slice = index, frame, view, slice_i


class TestDeltaLogOverflowCounters:
    def test_overflow_counts_per_slice(self):
        log = DeltaLog(cap=2)
        log.start("i", 0)
        log.start("i", 1)
        f0, f1 = _Frag(slice_i=0), _Frag(slice_i=1)
        for c in range(5):
            log.record(f0, [1], [c], [], [])
        log.record(f1, [1], [0], [], [])
        assert log.overflow_counts() == {"i/0": 1}
        snap = log.snapshot()
        assert snap["i/0"]["overflows"] == 1
        assert snap["i/0"]["overflowed"] is True
        assert snap["i/1"]["overflows"] == 0
        # lifetime: survives stop/start of the same slice
        log.stop("i", 0)
        log.start("i", 0)
        for c in range(5):
            log.record(f0, [1], [c], [], [])
        assert log.overflow_counts() == {"i/0": 2}


# ---------------------------------------------------------------------------
# engine integration over one real node
# ---------------------------------------------------------------------------


def _boot(tmp_path, name, **kwargs):
    s = Server(
        data_dir=str(tmp_path / name),
        host="127.0.0.1:0",
        cluster=Cluster(replica_n=1),
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        **kwargs,
    )
    s.open()
    return s


def _drain(client, sid, after):
    """Drain the retained update queue past ``after``; returns
    (last_update_or_None, new_cursor) and asserts version monotonicity."""
    last = None
    while True:
        status, data = client._request(
            "GET", f"/subscribe/{sid}/poll?after={after}&timeout_ms=100"
        )
        doc = json.loads(client._check(status, data))
        if doc.get("timeout"):
            return last, after
        assert doc["version"] > after, "versions must be monotonic"
        last, after = doc, doc["version"]


class TestEngineIncremental:
    def test_adjust_slice_and_full_paths(self, tmp_path):
        s = _boot(tmp_path, "node")
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            c.create_frame("i", "f", {})
            mgr = s.subscribe

            single = mgr.register("i", 'Subscribe(Count(Bitmap(rowID=1, frame="f")))')
            compound = mgr.register(
                "i",
                'Subscribe(Count(Union(Bitmap(rowID=1, frame="f"),'
                ' Bitmap(rowID=2, frame="f"))))',
            )
            assert single.value == 0 and compound.value == 0
            assert single.fast_row == 1  # the exact ±n path compiled

            for col in range(8):
                c.execute_query("i", f'SetBit(frame="f", rowID=1, columnID={col})')
            c.execute_query("i", 'SetBit(frame="f", rowID=2, columnID=100)')
            assert mgr.flush()
            assert single.value == 8
            assert compound.value == 9
            assert mgr.evals["adjust"] > 0, "single-leaf counts must ±n"
            assert mgr.evals["slice"] > 0, "compound trees re-eval the slice"

            c.execute_query("i", 'ClearBit(frame="f", rowID=1, columnID=3)')
            assert mgr.flush()
            assert single.value == 7 and compound.value == 8

            # a duplicate point write changes nothing and emits nothing
            v = single.version
            c.execute_query("i", 'SetBit(frame="f", rowID=1, columnID=0)')
            assert mgr.flush()
            assert single.value == 7 and single.version == v
        finally:
            s.close()

    def test_overflow_forces_full_reeval(self, tmp_path):
        s = _boot(tmp_path, "node", subscribe_delta_cap=4)
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            c.create_frame("i", "f", {})
            mgr = s.subscribe
            sub = mgr.register("i", 'Subscribe(Count(Bitmap(rowID=1, frame="f")))')
            # One import far over the 4-bit budget: the pending budget
            # overflows and the subscription re-bases from the planes.
            c.import_bits("i", "f", 0, [(1, col) for col in range(64)])
            assert mgr.flush()
            assert sub.value == 64
            assert mgr.overflows >= 1
            snap = mgr.snapshot()
            assert snap["counters"]["overflows"] >= 1
        finally:
            s.close()

    def test_unregister_and_limit(self, tmp_path):
        s = _boot(tmp_path, "node", subscribe_max_subscriptions=2)
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            c.create_frame("i", "f", {})
            mgr = s.subscribe
            a = mgr.register("i", 'Subscribe(Count(Bitmap(rowID=1, frame="f")))')
            mgr.register("i", 'Subscribe(Count(Bitmap(rowID=2, frame="f")))')
            with pytest.raises(SubscribeError):
                mgr.register("i", 'Subscribe(Count(Bitmap(rowID=3, frame="f")))')
            assert mgr.unregister(a.id)
            assert a.closed
            assert not mgr.unregister(a.id)
            mgr.register("i", 'Subscribe(Count(Bitmap(rowID=3, frame="f")))')
        finally:
            s.close()

    def test_http_surface(self, tmp_path):
        s = _boot(tmp_path, "node")
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            c.create_frame("i", "f", {})
            status, data = c._request(
                "POST",
                "/subscribe",
                body=json.dumps(
                    {"index": "i", "query": 'Subscribe(Count(Bitmap(rowID=1, frame="f")))'}
                ).encode(),
            )
            assert status == 201
            doc = json.loads(data)
            assert doc["version"] == 1 and doc["value"] == 0

            c.execute_query("i", 'SetBit(frame="f", rowID=1, columnID=9)')
            assert s.subscribe.flush()
            upd, _ = _drain(c, doc["id"], doc["version"])
            assert upd is not None and upd["value"] == 1

            # bad queries are client errors, not 500s
            for q in (
                "Count(Bitmap(rowID=1))",  # not a Subscribe
                "Subscribe(Count(Range(rowID=1, frame=f, start=0, end=1)))",
                "not pql",
            ):
                status, _ = c._request(
                    "POST", "/subscribe",
                    body=json.dumps({"index": "i", "query": q}).encode(),
                )
                assert status == 400, q
            status, _ = c._request("GET", "/subscribe/nope/poll")
            assert status == 404

            status, data = c._request("GET", "/debug/subscriptions")
            snap = json.loads(c._check(status, data))
            assert snap["count"] == 1
            assert snap["subscriptions"][0]["id"] == doc["id"]

            status, data = c._request("DELETE", f"/subscribe/{doc['id']}")
            assert status == 200
            # a poll against the unregistered subscription reports gone
            status, _ = c._request("GET", f"/subscribe/{doc['id']}/poll")
            assert status in (404, 410)
        finally:
            s.close()


# ---------------------------------------------------------------------------
# review regressions: the drain/plane-read double-apply race, the
# registration window, admission-shed batch loss, sibling data dirs
# ---------------------------------------------------------------------------


class TestDeltaFences:
    def test_write_landing_during_rebase_not_double_applied(self, tmp_path):
        s = _boot(tmp_path, "node")
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            c.create_frame("i", "f", {})
            mgr = s.subscribe
            sub = mgr.register("i", 'Subscribe(Count(Bitmap(rowID=1, frame="f")))')

            orig = mgr._slice_count
            raced = []

            def racing(sub_, slices):
                # Exact point write lands AFTER the drain but BEFORE
                # the plane read: the new base includes it, so its adj
                # delta (stamped at or below the base version) must be
                # dropped on the next batch, not re-applied.
                if not raced:
                    raced.append(True)
                    c.execute_query(
                        "i", 'SetBit(frame="f", rowID=1, columnID=7)'
                    )
                return orig(sub_, slices)

            mgr._slice_count = racing
            # an inexact single-bit import marks the slice dirty,
            # forcing the re-base that opens the race window
            c.import_bits("i", "f", 0, [(1, 3)])
            assert mgr.flush()
            mgr._slice_count = orig
            assert mgr.flush()
            assert sub.value == 2, "col 7 must be counted exactly once"
            want = s.executor.execute("i", Query(calls=[sub.inner]))[0]
            assert sub.value == want
        finally:
            s.close()

    def test_write_during_registration_snapshot_not_lost(self, tmp_path):
        s = _boot(tmp_path, "node")
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            c.create_frame("i", "f", {})
            mgr = s.subscribe

            orig = mgr._slice_count
            hit = []

            def racing(sub_, slices):
                # One write BEFORE the snapshot's plane read (included
                # in the base; its pending delta must be dropped) and
                # one AFTER it (not in the base; must be applied by
                # the notifier) — both inside the registration window.
                if not hit:
                    hit.append(True)
                    c.execute_query(
                        "i", 'SetBit(frame="f", rowID=1, columnID=1)'
                    )
                    res = orig(sub_, slices)
                    c.execute_query(
                        "i", 'SetBit(frame="f", rowID=1, columnID=2)'
                    )
                    return res
                return orig(sub_, slices)

            mgr._slice_count = racing
            sub = mgr.register("i", 'Subscribe(Count(Bitmap(rowID=1, frame="f")))')
            mgr._slice_count = orig
            assert mgr.flush()
            assert sub.value == 2, (
                "a write in the registration window must be neither "
                "lost nor double-counted"
            )
        finally:
            s.close()

    def test_admission_shed_requeues_batch(self, tmp_path):
        from pilosa_tpu.net.resilience import ShedError

        s = _boot(tmp_path, "node")
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            c.create_frame("i", "f", {})
            mgr = s.subscribe
            sub = mgr.register("i", 'Subscribe(Count(Bitmap(rowID=1, frame="f")))')

            class _Ticket:
                def release(self):
                    pass

            class _Shedding:
                def __init__(self, fails):
                    self.fails = fails
                    self.sheds = 0

                def acquire(self, cls, deadline=None):
                    if self.fails > 0:
                        self.fails -= 1
                        self.sheds += 1
                        raise ShedError("subscribe lane saturated")
                    return _Ticket()

            gate = _Shedding(fails=2)
            mgr.admission = gate
            c.execute_query("i", 'SetBit(frame="f", rowID=1, columnID=5)')
            assert mgr.flush(timeout=15.0)
            assert gate.sheds == 2, "the shed path must have been taken"
            assert sub.value == 1, "drained deltas must survive a shed"
        finally:
            s.close()

    def test_sibling_data_dir_not_cross_matched(self, tmp_path):
        s = _boot(tmp_path, "n1")
        try:
            mgr = s.subscribe

            class F:
                pass

            own = F()
            own.path = str(tmp_path / "n1" / "i" / "f" / "standard" / "0")
            sibling = F()
            sibling.path = str(tmp_path / "n10" / "i" / "f" / "standard" / "0")
            assert not mgr._foreign(own)
            assert mgr._foreign(sibling), (
                "/…/n10 must not prefix-match the /…/n1 node"
            )
        finally:
            s.close()


# ---------------------------------------------------------------------------
# randomized byte-identity storm: every delivered value equals the
# from-scratch hosteval pull at quiescence
# ---------------------------------------------------------------------------


class TestStorm:
    def test_randomized_storm_matches_oracle(self, tmp_path):
        rng = random.Random(0xC0FFEE)
        s = _boot(tmp_path, "node", subscribe_delta_cap=200)
        try:
            c = InternalClient(s.host, timeout=10.0)
            c.create_index("i")
            c.create_frame("i", "f", {})
            c.create_frame("i", "g", {})
            c.create_frame("i", "b", {"rangeEnabled": True})
            c.create_field("i", "b", "v", 0, 1000)
            mgr = s.subscribe

            subs = []
            for row in range(4):
                subs.append(mgr.register(
                    "i", f'Subscribe(Count(Bitmap(rowID={row}, frame="f")))'
                ))
            subs.append(mgr.register(
                "i",
                'Subscribe(Count(Intersect(Bitmap(rowID=0, frame="f"),'
                ' Bitmap(rowID=1, frame="f"))))',
            ))
            subs.append(mgr.register(
                "i",
                'Subscribe(Count(Union(Bitmap(rowID=2, frame="f"),'
                ' Bitmap(rowID=0, frame="g"))))',
            ))
            subs.append(mgr.register(
                "i",
                'Subscribe(Count(Difference(Bitmap(rowID=0, frame="f"),'
                ' Bitmap(rowID=1, frame="f"))))',
            ))
            subs.append(mgr.register("i", 'Subscribe(Count(Range(frame="b", v > 500)))'))
            topn = mgr.register("i", 'Subscribe(TopN(frame="f", n=3))')
            subs.append(topn)
            cursors = {sub.id: sub.version for sub in subs}

            def check_all():
                assert mgr.flush()
                for sub in subs:
                    want = s.executor.execute(
                        "i", Query(calls=[sub.inner])
                    )[0]
                    assert sub.value == want, (sub.pql, sub.value, want)
                    # the update stream is monotonic and ends at the
                    # oracle value
                    upd, cursors[sub.id] = _drain(c, sub.id, cursors[sub.id])
                    if upd is not None:
                        assert upd["value"] == sub.value_json

            for burst in range(6):
                for _ in range(40):
                    op = rng.random()
                    row = rng.randrange(4)
                    col = rng.randrange(2 * SLICE_WIDTH)
                    frame = rng.choice(["f", "f", "f", "g"])
                    if op < 0.55:
                        c.execute_query(
                            "i",
                            f'SetBit(frame="{frame}", rowID={row}, columnID={col})',
                        )
                    elif op < 0.8:
                        c.execute_query(
                            "i",
                            f'ClearBit(frame="{frame}", rowID={row}, columnID={col})',
                        )
                    else:
                        c.import_value(
                            "i", "b", "v", col // SLICE_WIDTH,
                            [col], [rng.randrange(1000)],
                        )
                if burst == 3:
                    # bulk import mid-storm: inexact notifications +
                    # possible overflow re-base
                    c.import_bits(
                        "i", "f", 0,
                        [(rng.randrange(4), rng.randrange(SLICE_WIDTH))
                         for _ in range(300)],
                    )
                s._tick_max_slices()
                check_all()

            # incremental arithmetic never drifted: byte-identical to a
            # from-scratch hosteval pull over every slice
            idx = s.holder.index("i")
            all_slices = list(range(idx.max_slice() + 1))
            for sub in subs:
                if sub.kind != reg.KIND_COUNT:
                    continue
                want = s.executor.hosteval.count_total(
                    "i", sub.tree, all_slices
                )
                assert sub.value == want, sub.pql
        finally:
            s.close()


# ---------------------------------------------------------------------------
# a subscription survives a live 2->3 resize
# ---------------------------------------------------------------------------


def _wire(servers, hosts):
    for s in servers:
        for h in hosts:
            if s.cluster.node_by_host(h) is None:
                s.cluster.add_node(h)
        s.cluster.nodes.sort(key=lambda n: n.host)


def _wait_resize(host, timeout=90.0):
    client = InternalClient(host, timeout=10.0)
    deadline = time.time() + timeout
    while time.time() < deadline:
        status, data = client._request("GET", "/debug/rebalance")
        snap = json.loads(client._check(status, data))
        if not snap.get("running") and snap.get("transition") is None:
            return snap
        time.sleep(0.1)
    raise AssertionError("resize did not complete")


class TestResizeSurvival:
    def test_subscription_survives_2_to_3_resize(self, tmp_path):
        def boot(name, ring):
            cluster = Cluster(replica_n=1)
            for h in ring:
                cluster.add_node(h)
            s = Server(
                data_dir=str(tmp_path / name),
                host="127.0.0.1:0",
                cluster=cluster,
                anti_entropy_interval=3600,
                polling_interval=3600,
                cache_flush_interval=3600,
                rebalance_release_delay_ms=0.0,
                subscribe_refresh_ms=100.0,
            )
            s.open()
            return s

        s1 = boot("n1", ())
        s2 = boot("n2", (s1.host,))
        servers = [s1, s2]
        try:
            hosts2 = sorted([s1.host, s2.host])
            _wire(servers, hosts2)
            for s in servers:
                s.holder.create_index_if_not_exists("i")
                s.holder.index("i").create_frame_if_not_exists("f")
            c = InternalClient(s1.host, timeout=10.0)
            n_slices = 4
            for sl in range(n_slices):
                c.execute_query(
                    "i",
                    f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH + sl})',
                )
            for s in servers:
                s._tick_max_slices()

            sub = s1.subscribe.register(
                "i", 'Subscribe(Count(Bitmap(rowID=1, frame="f")))'
            )
            assert sub.value == n_slices
            cursor = sub.version
            epoch0 = sub.epoch

            s3 = boot("n3", hosts2)
            servers.append(s3)
            hosts3 = sorted(hosts2 + [s3.host])
            status, data = c._request(
                "POST", "/cluster/resize",
                body=json.dumps({"hosts": hosts3}).encode(),
            )
            c._check(status, data)
            _wait_resize(s1.host)

            # writes keep landing after the cutover; the subscription
            # keeps tracking them through the new topology
            for sl in range(n_slices):
                for attempt in range(20):
                    try:
                        c.execute_query(
                            "i",
                            f'SetBit(frame="f", rowID=1,'
                            f' columnID={sl * SLICE_WIDTH + 500})',
                        )
                        break
                    except (ClientError, ConnectionError):
                        time.sleep(0.1)

            want = 2 * n_slices
            deadline = time.time() + 30
            while time.time() < deadline and sub.value != want:
                time.sleep(0.1)
            assert sub.value == want, (sub.value, want)
            assert not sub.closed
            assert sub.epoch > epoch0, "topology move must re-stamp the epoch"
            assert s1.subscribe.epoch_flips >= 1

            # no lost updates: the stream drains monotonically to the
            # final absolute value
            upd, _ = _drain(c, sub.id, cursor)
            assert upd is not None and upd["value"] == want
        finally:
            for s in servers:
                s.close()
