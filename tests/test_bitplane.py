"""Bit-plane op parity vs numpy (the kernel-level parity tier, replacing
the reference's asm-vs-Go popcount tests, roaring/assembly_test.go:20-43)."""

import numpy as np
import pytest

from pilosa_tpu.ops import bitplane as bp


def random_row(rng, density=0.01):
    n = int(bp.SLICE_WIDTH * density)
    offs = rng.choice(bp.SLICE_WIDTH, size=n, replace=False)
    return bp.np_columns_to_row(offs), np.sort(offs)


def np_popcount(words):
    return int(np.unpackbits(words.view(np.uint8)).sum())


def test_set_clear_contains(rng):
    plane = bp.empty_plane(4)
    assert bp.np_set_bit(plane, 5)
    assert not bp.np_set_bit(plane, 5)
    assert bp.np_contains(plane, 5)
    assert bp.np_set_bit(plane, bp.SLICE_WIDTH + 7)  # row 1
    assert plane[1, 0] == 1 << 7
    assert bp.np_clear_bit(plane, 5)
    assert not bp.np_clear_bit(plane, 5)
    assert not bp.np_contains(plane, 5)


def test_columns_roundtrip(rng):
    row, offs = random_row(rng)
    got = bp.np_row_to_columns(row)
    assert np.array_equal(got, offs.astype(np.uint64))


def test_count_ops_match_numpy(rng):
    a, _ = random_row(rng, 0.02)
    b, _ = random_row(rng, 0.02)
    assert int(bp.count(a)) == np_popcount(a)
    assert int(bp.count_and(a, b)) == np_popcount(a & b)
    assert int(bp.count_or(a, b)) == np_popcount(a | b)
    assert int(bp.count_xor(a, b)) == np_popcount(a ^ b)
    assert int(bp.count_andnot(a, b)) == np_popcount(a & ~b)


def test_materializing_ops(rng):
    a, _ = random_row(rng, 0.02)
    b, _ = random_row(rng, 0.02)
    assert np.array_equal(np.asarray(bp.and_(a, b)), a & b)
    assert np.array_equal(np.asarray(bp.or_(a, b)), a | b)
    assert np.array_equal(np.asarray(bp.xor(a, b)), a ^ b)
    assert np.array_equal(np.asarray(bp.andnot(a, b)), a & ~b)


@pytest.mark.parametrize(
    "start,end",
    [(0, 0), (0, 1), (31, 33), (0, bp.SLICE_WIDTH), (100, 100), (65, 64), (1000, 123456)],
)
def test_count_range(rng, start, end):
    a, offs = random_row(rng, 0.01)
    expect = int(((offs >= start) & (offs < end)).sum())
    assert int(bp.count_range(a, start, end)) == expect


def test_flip_range(rng):
    a, offs = random_row(rng, 0.001)
    start, end = 1000, 200000
    flipped = np.asarray(bp.flip_range(a, start, end))
    # bits inside [start,end) toggled, outside unchanged
    got = set(int(x) for x in bp.np_row_to_columns(flipped))
    expect = set(int(o) for o in offs)
    expect = (expect - set(range(start, end))) | (
        set(range(start, end)) - set(int(o) for o in offs)
    )
    assert got == expect


def test_row_counts_and_top_counts(rng):
    plane = bp.empty_plane(8)
    for r in range(8):
        n = (r + 1) * 100
        offs = rng.choice(bp.SLICE_WIDTH, size=n, replace=False)
        plane[r] = bp.np_columns_to_row(offs)
    counts = np.asarray(bp.row_counts(plane))
    for r in range(8):
        assert counts[r] == np_popcount(plane[r])
    src = plane[3]
    tc = np.asarray(bp.top_counts(plane, src))
    for r in range(8):
        assert tc[r] == np_popcount(plane[r] & src)


def test_score_planes_parity(rng):
    """The fused cross-fragment TopN scorer (gather + AND + popcount +
    rowsum straight from plane mirrors) matches numpy bit-for-bit, in
    both src modes."""
    import jax.numpy as jnp

    n_frag, plane_rows, cand = 3, 16, 8
    planes_np = [
        rng.integers(0, 2**32, size=(plane_rows, bp.WORDS_PER_SLICE), dtype=np.uint32)
        for _ in range(n_frag)
    ]
    slots = rng.integers(0, plane_rows, size=(n_frag, cand)).astype(np.int32)
    src_slots = rng.integers(0, plane_rows, size=n_frag).astype(np.int32)
    planes = tuple(jnp.asarray(p) for p in planes_np)

    want = np.zeros((n_frag, cand), np.int32)
    for f in range(n_frag):
        src = planes_np[f][src_slots[f]]
        for r in range(cand):
            want[f, r] = np.bitwise_count(
                planes_np[f][slots[f, r]] & src
            ).sum()

    got = np.asarray(bp.score_planes(planes, slots, src_slots=src_slots))
    np.testing.assert_array_equal(got, want)

    srcs = np.stack([planes_np[f][src_slots[f]] for f in range(n_frag)])
    got2 = np.asarray(bp.score_planes(planes, slots, srcs=srcs))
    np.testing.assert_array_equal(got2, want)


def test_top_k_tie_break(rng):
    counts = np.array([5, 9, 9, 1, 9, 0], dtype=np.int32)
    topc, topidx = bp.top_k(counts, 3)
    assert list(np.asarray(topc)) == [9, 9, 9]
    assert list(np.asarray(topidx)) == [1, 2, 4]


def test_bulk_set(rng):
    plane = bp.empty_plane(4)
    rows = np.array([0, 0, 1, 3, 3])
    offs = np.array([0, 31, 32, 5, 5])
    bp.np_set_bulk(plane, rows, offs)
    assert bp.np_contains(plane, 0)
    assert bp.np_contains(plane, 31)
    assert bp.np_contains(plane, bp.SLICE_WIDTH + 32)
    assert bp.np_contains(plane, 3 * bp.SLICE_WIDTH + 5)
    assert np_popcount(plane) == 4


def test_pad_rows():
    assert bp.pad_rows(0) == bp.ROW_BLOCK
    assert bp.pad_rows(1) == bp.ROW_BLOCK
    assert bp.pad_rows(8) == 8
    assert bp.pad_rows(9) == 16


