"""Quorum replication (pilosa_tpu/replicate): W-of-N write units, the
version store, the hinted-handoff log, and end-to-end chaos over real
HTTP nodes — kill a replica under sustained quorum writes, restart it,
and prove zero lost writes + checksum convergence WITHOUT an
anti-entropy tick; read-your-writes at quorum settings via synchronous
read-repair; sub-W writes failing loudly (the PR-5 any-ack bugfix)."""

from __future__ import annotations

import json
import threading
import time

import pytest

from pilosa_tpu.cluster.topology import Cluster
from pilosa_tpu.net.client import ClientError, InternalClient
from pilosa_tpu.net.server import Server
from pilosa_tpu.ops.bitplane import SLICE_WIDTH
from pilosa_tpu.replicate import hints as hints_mod
from pilosa_tpu.replicate import (
    HintLog,
    VersionStore,
    required_acks,
    validate_level,
)

# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


class TestRequiredAcks:
    def test_levels(self):
        assert required_acks("one", 3) == 1
        assert required_acks("quorum", 1) == 1
        assert required_acks("quorum", 2) == 2
        assert required_acks("quorum", 3) == 2
        assert required_acks("quorum", 4) == 3
        assert required_acks("quorum", 5) == 3
        assert required_acks("all", 3) == 3
        assert required_acks("all", 0) == 1  # clamped to >= 1 replica

    def test_unknown_level_raises(self):
        with pytest.raises(ValueError):
            required_acks("most", 3)
        with pytest.raises(ValueError):
            validate_level("banana")


class TestVersionStore:
    def test_bump_is_monotonic_per_slice(self):
        vs = VersionStore()
        assert vs.bump("i", 0) == 1
        assert vs.bump("i", 0) == 2
        assert vs.bump("i", 1) == 1
        assert vs.get("i", 0) == 2
        assert vs.get_many("i", [0, 1, 2]) == {0: 2, 1: 1, 2: 0}

    def test_observe_max_merges(self):
        vs = VersionStore()
        vs.bump("i", 0)
        assert vs.observe("i", 0, 9) == 9
        # never backwards
        assert vs.observe("i", 0, 3) == 9
        assert vs.get("i", 0) == 9

    def test_doc_roundtrip(self):
        vs = VersionStore()
        vs.bump("i", 0)
        vs.observe("i", 5, 7)
        vs2 = VersionStore()
        vs2.load_doc(vs.to_doc())
        assert vs2.get("i", 0) == 1
        assert vs2.get("i", 5) == 7

    def test_snapshot_summarizes(self):
        vs = VersionStore()
        for s in range(4):
            vs.observe("i", s, s + 1)
        snap = vs.snapshot()
        assert snap["i"]["slices"] == 4
        assert snap["i"]["max"] == 4


class _Frag:
    def __init__(self, index="i", frame="f", view="standard", slice_i=0):
        self.index, self.frame, self.view, self.slice = index, frame, view, slice_i
        self.path = "/data/n0/i/f/standard/0"


class TestHintLog:
    def test_capture_scope_records_local_writes(self):
        buf: list = []
        with hints_mod.capture(buf):
            hints_mod.record_local_write(_Frag(), (1,), (10,), (), ())
        # outside the scope: no-op
        hints_mod.record_local_write(_Frag(), (2,), (20,), (), ())
        assert buf == [("i", 0, "f", "standard", [1], [10], [], [])]

    def test_queue_drain_order_and_requeue(self):
        log = HintLog(cap=100)
        assert log.queue_pql("h1", "i", 0, "SetBit(...)")
        log.queue_views(
            "h1", [("i", 0, "f", "standard", [2], [20], [], [])]
        )
        assert log.backlog("h1") == 2
        groups = log.drain("h1")
        assert [(g[0], g[1], len(g[2])) for g in groups] == [("i", 0, 2)]
        assert groups[0][2][0][0] == "pql"
        assert log.backlog("h1") == 0
        # a dead push requeues head-first
        log.queue_pql("h1", "i", 0, "later")
        log.requeue("h1", "i", 0, groups[0][2])
        drained = log.drain("h1")[0][2]
        assert [e[0] for e in drained] == ["pql", "views", "pql"]

    def test_cap_overflow_drops_slice_and_counts(self):
        log = HintLog(cap=3)
        for k in range(5):
            log.queue_views(
                "h1", [("i", 0, "f", "standard", [k], [k], [], [])]
            )
        assert log.dropped > 0
        # the overflowed slice refuses further hints (a partial stream
        # replays to a state that is neither old nor new)...
        assert not log.queue_pql("h1", "i", 0, "x")
        # ...but other slices are unaffected
        assert log.queue_pql("h1", "i", 1, "x")
        assert log.backlog("h1") == 1
        # the drain reports the overflow so the replayer reconciles by
        # checksum instead of trusting the stream; afterwards the slice
        # accepts hints again
        over = {(g[0], g[1]): g[3] for g in log.drain("h1")}
        assert over[("i", 0)] is True and over[("i", 1)] is False
        assert log.queue_pql("h1", "i", 0, "y")

    def test_payload_kind_validated(self):
        log = HintLog()
        with pytest.raises(ValueError):
            log.queue_payload("h1", "i", 0, "csv", b"x", 1)

    def test_note_replay_tracks_outcome(self):
        log = HintLog()
        log.queue_pql("h1", "i", 0, "x")
        log.drain("h1")
        log.note_replay("h1", 1)
        snap = log.snapshot()
        assert snap["targets"]["h1"]["replayed"] == 1
        assert "lastError" not in snap["targets"]["h1"]
        log.note_replay("h1", 0, error="boom")
        assert log.snapshot()["targets"]["h1"]["lastError"] == "boom"


# ---------------------------------------------------------------------------
# end-to-end: 3 replicas over real HTTP nodes
# ---------------------------------------------------------------------------

N_SLICES = 4


def _boot(tmp_path, name, host="127.0.0.1:0", ring=(), replay_s=0.2, **kw):
    cluster = Cluster(replica_n=3)
    for h in ring:
        cluster.add_node(h)
    s = Server(
        data_dir=str(tmp_path / name),
        host=host,
        cluster=cluster,
        anti_entropy_interval=3600,  # anti-entropy NEVER ticks in tests
        polling_interval=3600,
        cache_flush_interval=3600,
        breaker_open_ms=300.0,
        **kw,
    )
    s.replication.replay_interval_s = replay_s
    s.open()
    return s


def _wire(servers, hosts):
    for s in servers:
        for h in hosts:
            if s.cluster.node_by_host(h) is None:
                s.cluster.add_node(h)
        s.cluster.nodes.sort(key=lambda n: n.host)


def _schema(servers):
    for s in servers:
        s.holder.create_index_if_not_exists("i")
        s.holder.index("i").create_frame_if_not_exists("f")


def _seed(client, servers):
    for sl in range(N_SLICES):
        client.execute_query(
            "i", f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH + sl})'
        )
    for s in servers:
        s._tick_max_slices()


def _checksums(server, sl):
    return server.rebalance.delta_action(
        {"index": "i", "slice": sl, "action": "checksum"}
    )["checksums"]


def _local_row_bits(server, row):
    total = 0
    view = server.holder.index("i").frame("f").view("standard")
    for sl in range(N_SLICES):
        frag = view.fragment(sl)
        if frag is not None:
            total += frag._count_of.get(row, 0)
    return total


def _debug_replication(host):
    client = InternalClient(host, timeout=10.0)
    status, data = client._request("GET", "/debug/replication")
    return json.loads(client._check(status, data))


class TestChaosKillRestartConverge:
    def test_zero_lost_writes_without_anti_entropy(self, tmp_path):
        """ISSUE 14 acceptance: kill a replica under sustained quorum
        writes, restart it, and every write converges onto it from
        HINT REPLAY alone — checksum agreement across all replicas with
        the anti-entropy loop disabled (interval 3600 s)."""
        servers = [_boot(tmp_path, f"n{i}") for i in range(3)]
        stop = threading.Event()
        try:
            hosts = sorted(s.host for s in servers)
            _wire(servers, hosts)
            _schema(servers)
            s0 = servers[0]
            c0 = InternalClient(s0.host, timeout=10.0)
            _seed(c0, servers)

            victim = servers[2]
            victim_host = victim.host

            errors: list[str] = []
            written: list[int] = []

            def writer():
                cw = InternalClient(s0.host, timeout=10.0)
                k = 0
                while not stop.is_set():
                    col = (k % N_SLICES) * SLICE_WIDTH + 100 + k // N_SLICES
                    try:
                        cw.execute_query(
                            "i", f'SetBit(frame="f", rowID=3, columnID={col})'
                        )
                        written.append(col)
                    except (ClientError, ConnectionError) as e:
                        errors.append(f"writer: {e}")
                        return
                    k += 1
                    time.sleep(0.005)

            t = threading.Thread(target=writer, daemon=True)
            t.start()
            time.sleep(0.2)

            # KILL the replica mid-storm: quorum (2 of 3) writes keep
            # succeeding, each queuing a hint for the dead host.
            victim.close()
            deadline = time.time() + 20
            while (
                time.time() < deadline
                and s0.replication.hints.backlog(victim_host) < 5
            ):
                time.sleep(0.05)
            assert s0.replication.hints.backlog(victim_host) >= 5, (
                "sustained writes queued no hints for the dead replica"
            )
            snap = _debug_replication(s0.host)
            assert victim_host in snap["hints"]["targets"]

            # RESTART it (same identity/dir) while writes continue; the
            # breaker's open->half-open transition triggers replay.
            victim = _boot(tmp_path, "n2", host=victim_host, ring=hosts)
            servers[2] = victim
            deadline = time.time() + 30
            while (
                time.time() < deadline
                and s0.replication.hints.backlog(victim_host) > 0
            ):
                time.sleep(0.1)
            stop.set()
            t.join(timeout=10.0)
            assert not errors, errors
            assert written, "writer made no progress"
            # Drain hints for writes issued after the join as well;
            # backlog==0 only means "drained", so convergence is
            # polled on the authoritative signal: checksum agreement
            # (a drained hint may still be applying over HTTP).
            deadline = time.time() + 30
            while time.time() < deadline:
                if s0.replication.hints.backlog(victim_host) == 0 and all(
                    _checksums(s0, sl) == _checksums(victim, sl)
                    for sl in range(N_SLICES)
                ):
                    break
                time.sleep(0.1)
            assert s0.replication.hints.backlog(victim_host) == 0

            # ZERO lost writes, converged WITHOUT anti-entropy: the
            # restarted replica's LOCAL fragments carry every confirmed
            # write and checksum-agree with the survivors.
            expect = len(set(written))
            assert _local_row_bits(victim, 3) == expect
            for sl in range(N_SLICES):
                assert _checksums(s0, sl) == _checksums(victim, sl), (
                    f"slice {sl} diverged after hint replay"
                )
        finally:
            stop.set()
            for s in servers:
                s.close()


class TestFaultInjectedPartition:
    def test_injected_write_leg_errors_queue_hints(self, tmp_path):
        """testing/faults.py chaos: the replica PROCESS stays up but its
        write legs error at the rpc.send boundary (a partitioned
        network, not a dead node) — quorum writes still succeed, hints
        queue, and a forced replay after the partition heals converges
        the replica without anti-entropy."""
        from pilosa_tpu.testing import faults

        servers = [_boot(tmp_path, f"n{i}", replay_s=3600.0) for i in range(3)]
        try:
            hosts = sorted(s.host for s in servers)
            _wire(servers, hosts)
            _schema(servers)
            s0 = servers[0]
            victim = servers[2]
            c0 = InternalClient(s0.host, timeout=10.0)
            _seed(c0, servers)

            faults.install(
                f"rpc.send:host={victim.host},path=/index/*/query,mode=error"
            )
            try:
                cols = [SLICE_WIDTH * 2 + 300 + k for k in range(5)]
                for col in cols:
                    c0.execute_query(
                        "i", f'SetBit(frame="f", rowID=4, columnID={col})'
                    )
            finally:
                faults.clear()
            assert s0.replication.hints.backlog(victim.host) >= len(cols)
            assert _local_row_bits(victim, 4) == 0

            # partition healed: once the victim's breaker re-admits
            # traffic (open -> half-open after breaker_open_ms), the
            # replay — which IS the half-open probe — converges it.
            time.sleep(0.35)
            replayed = s0.replication.replay_now(victim.host)
            assert replayed[victim.host] >= len(cols)
            assert _local_row_bits(victim, 4) == len(cols)
            for sl in range(N_SLICES):
                assert _checksums(s0, sl) == _checksums(victim, sl)
        finally:
            faults.clear()
            for s in servers:
                s.close()


class TestReadYourWrites:
    def test_quorum_read_repairs_stale_replica(self, tmp_path):
        """W=quorum + R=quorum overlap: a write acked while one replica
        was down MUST be visible to a quorum read coordinated by that
        stale replica — the version check detects the lag and the
        synchronous read-repair converges it before serving."""
        # Replay disabled (huge interval): the stale replica stays
        # stale unless the READ path repairs it.
        servers = [
            _boot(tmp_path, f"n{i}", replay_s=3600.0) for i in range(3)
        ]
        try:
            hosts = sorted(s.host for s in servers)
            _wire(servers, hosts)
            _schema(servers)
            s0 = servers[0]
            c0 = InternalClient(s0.host, timeout=10.0)
            _seed(c0, servers)

            victim = servers[2]
            victim_host = victim.host
            # A slice whose PRIMARY is the victim: the default "one"
            # read through the victim serves its own (stale) fragment.
            target_slice = next(
                sl
                for sl in range(N_SLICES)
                if s0.cluster.fragment_nodes("i", sl)[0].host == victim_host
            )
            col = target_slice * SLICE_WIDTH + 777

            victim.close()
            # Quorum write while the replica is down: 2 of 3 ack.
            c0.execute_query(
                "i", f'SetBit(frame="f", rowID=7, columnID={col})'
            )

            victim = _boot(
                tmp_path, "n2", host=victim_host, ring=hosts,
                replay_s=3600.0,
            )
            servers[2] = victim
            cv = InternalClient(victim.host, timeout=30.0)

            # At consistency "one" the victim serves its own stale
            # fragment: the write is invisible.
            got = cv.execute_query(
                "i",
                'Count(Bitmap(frame="f", rowID=7))',
                slices=[target_slice],
            )
            assert got[0] == 0, "victim unexpectedly already converged"

            # At quorum the version check sees the lag, read-repair
            # pushes newest -> stale, and the SAME coordinator answers
            # with the write: read-your-writes.
            got = cv.execute_query(
                "i",
                'Count(Bitmap(frame="f", rowID=7))',
                slices=[target_slice],
                trace_headers={"X-Read-Consistency": "quorum"},
            )
            assert got[0] == 1
            # ...and the repair actually converged the local fragment,
            # so even "one" reads see it now.
            got = cv.execute_query(
                "i",
                'Count(Bitmap(frame="f", rowID=7))',
                slices=[target_slice],
            )
            assert got[0] == 1
        finally:
            for s in servers:
                s.close()


class TestSubQuorumFailsLoudly:
    def test_write_below_w_raises_and_queues_hint(self, tmp_path):
        """The PR-5 bugfix satellite: a write that cannot gather W acks
        FAILS the request loudly (naming the counts) instead of
        reporting success because someone acked — and the failed
        replica's hint is queued regardless."""
        servers = [_boot(tmp_path, f"n{i}") for i in range(3)]
        try:
            hosts = sorted(s.host for s in servers)
            _wire(servers, hosts)
            _schema(servers)
            s0 = servers[0]
            c0 = InternalClient(s0.host, timeout=10.0)
            _seed(c0, servers)

            victim_host = servers[2].host
            servers[2].close()

            # consistency=all with a dead replica: loud failure.
            with pytest.raises(ClientError) as ei:
                c0.execute_query(
                    "i",
                    f'SetBit(frame="f", rowID=5, columnID={SLICE_WIDTH + 9})',
                    trace_headers={"X-Write-Consistency": "all"},
                )
            assert "2 of 3" in str(ei.value) and "need 3" in str(ei.value)
            assert s0.replication.hints.backlog(victim_host) >= 1

            # default quorum still succeeds (2 of 3) and hints too.
            before = s0.replication.hints.backlog(victim_host)
            c0.execute_query(
                "i",
                f'SetBit(frame="f", rowID=5, columnID={SLICE_WIDTH + 10})',
            )
            assert s0.replication.hints.backlog(victim_host) > before

            # junk consistency is a 400, not a silent default.
            with pytest.raises(ClientError) as ei:
                c0.execute_query(
                    "i",
                    'Count(Bitmap(frame="f", rowID=1))',
                    trace_headers={"X-Read-Consistency": "banana"},
                )
            assert ei.value.status == 400
        finally:
            for s in servers[:2]:
                s.close()

    def test_import_fanout_w_of_n(self, tmp_path):
        """Client import fan-out under the same contract: sub-W raises
        naming the dead host; at a met W the dead replica's payload is
        queued as a hint on an acked node and replays on recovery."""
        import numpy as np

        servers = [_boot(tmp_path, f"n{i}") for i in range(3)]
        try:
            hosts = sorted(s.host for s in servers)
            _wire(servers, hosts)
            _schema(servers)
            s0 = servers[0]
            c0 = InternalClient(s0.host, timeout=10.0)
            _seed(c0, servers)

            victim = servers[2]
            victim_host = victim.host
            victim.close()

            bits = (
                np.asarray([9, 9, 9], dtype=np.uint64),
                np.asarray([11, 12, 13], dtype=np.uint64),
            )
            with pytest.raises(ClientError) as ei:
                c0.import_bits("i", "f", 0, bits, consistency="all")
            assert victim_host in str(ei.value)
            assert "need 3" in str(ei.value)

            # quorum succeeds and the dead host's payload parks as a
            # hint on one of the acked nodes.
            c0.import_bits("i", "f", 0, bits, consistency="quorum")
            holder = next(
                s
                for s in servers[:2]
                if s.replication.hints.backlog(victim_host) > 0
            )

            victim = _boot(tmp_path, "n2", host=victim_host, ring=hosts)
            servers[2] = victim
            deadline = time.time() + 20
            while (
                time.time() < deadline
                and holder.replication.hints.backlog(victim_host) > 0
            ):
                time.sleep(0.1)
            assert holder.replication.hints.backlog(victim_host) == 0
            assert _local_row_bits(victim, 9) == 3
        finally:
            for s in servers:
                s.close()


class TestSyncerVersionSkip:
    def test_in_sync_slices_skip_and_lag_attributes_cause(self, tmp_path):
        """Anti-entropy becomes the backstop: replica-agreed versions
        skip the block checksum walk; a lagging replica attributes the
        sweep to cause:missed-hint; full=True never skips."""
        from pilosa_tpu.sync.syncer import HolderSyncer

        servers = [_boot(tmp_path, f"n{i}") for i in range(3)]
        try:
            hosts = sorted(s.host for s in servers)
            _wire(servers, hosts)
            _schema(servers)
            s0 = servers[0]
            c0 = InternalClient(s0.host, timeout=10.0)
            _seed(c0, servers)

            syncer = HolderSyncer(
                holder=s0.holder,
                host=s0.host,
                cluster=s0.cluster,
                replication=s0.replication,
            )
            idx_max = N_SLICES - 1
            # every replica applied every write: versions agree -> skip
            for sl in range(N_SLICES):
                assert syncer.slice_cause("i", sl, idx_max) is None

            # lag one replica's version: provably missed writes
            servers[2].replication.versions.observe("i", 0, 999)
            syncer2 = HolderSyncer(
                holder=s0.holder,
                host=s0.host,
                cluster=s0.cluster,
                replication=s0.replication,
            )
            assert syncer2.slice_cause("i", 0, idx_max) == "missed-hint"

            # full sweep: never skips, cause is plain drift
            syncer3 = HolderSyncer(
                holder=s0.holder,
                host=s0.host,
                cluster=s0.cluster,
                replication=s0.replication,
                full=True,
            )
            assert syncer3.slice_cause("i", 0, idx_max) == "drift"
            # without replication wired: legacy behavior (always walk)
            syncer4 = HolderSyncer(
                holder=s0.holder, host=s0.host, cluster=s0.cluster
            )
            assert syncer4.slice_cause("i", 0, idx_max) == "drift"
        finally:
            for s in servers:
                s.close()


class TestVersionPersistence:
    def test_versions_survive_clean_restart(self, tmp_path):
        s = _boot(tmp_path, "n0")
        try:
            host = s.host
            s.holder.create_index_if_not_exists("i")
            s.holder.index("i").create_frame_if_not_exists("f")
            c = InternalClient(s.host, timeout=10.0)
            c.execute_query("i", 'SetBit(frame="f", rowID=1, columnID=3)')
            v = s.replication.versions.get("i", 0)
            assert v >= 1
        finally:
            s.close()
        s = _boot(tmp_path, "n0", host=host, ring=[host])
        try:
            assert s.replication.versions.get("i", 0) >= v
        finally:
            s.close()
