"""Time-quantum view tests (parity tier for time_test.go)."""

from datetime import datetime

import pytest

from pilosa_tpu.core import timequantum as tq


def test_parse():
    assert tq.parse_time_quantum("ymdh") == "YMDH"
    assert tq.parse_time_quantum("") == ""
    with pytest.raises(tq.InvalidTimeQuantumError):
        tq.parse_time_quantum("YMH")
    with pytest.raises(tq.InvalidTimeQuantumError):
        tq.parse_time_quantum("X")


def test_view_by_time_unit():
    t = datetime(2017, 3, 5, 14)
    assert tq.view_by_time_unit("std", t, "Y") == "std_2017"
    assert tq.view_by_time_unit("std", t, "M") == "std_201703"
    assert tq.view_by_time_unit("std", t, "D") == "std_20170305"
    assert tq.view_by_time_unit("std", t, "H") == "std_2017030514"
    assert tq.view_by_time_unit("std", t, "X") == ""


def test_views_by_time():
    t = datetime(2017, 3, 5, 14)
    assert tq.views_by_time("v", t, "YMDH") == [
        "v_2017", "v_201703", "v_20170305", "v_2017030514",
    ]
    assert tq.views_by_time("v", t, "D") == ["v_20170305"]


def test_views_by_time_range_hour_span():
    # 2017-03-05 22:00 .. 2017-03-06 02:00 with DH: hours up to midnight,
    # then... next day not complete, so hours again
    got = tq.views_by_time_range(
        "v", datetime(2017, 3, 5, 22), datetime(2017, 3, 6, 2), "DH"
    )
    assert got == ["v_2017030522", "v_2017030523", "v_2017030600", "v_2017030601"]


def test_views_by_time_range_full_day():
    got = tq.views_by_time_range(
        "v", datetime(2017, 3, 5, 22), datetime(2017, 3, 7, 0), "DH"
    )
    assert got == ["v_2017030522", "v_2017030523", "v_20170306"]


def test_views_by_time_range_month_cover():
    got = tq.views_by_time_range(
        "v", datetime(2017, 1, 30), datetime(2017, 3, 2), "MD"
    )
    assert got == ["v_20170130", "v_20170131", "v_201702", "v_20170301"]


def test_views_by_time_range_year():
    got = tq.views_by_time_range(
        "v", datetime(2016, 1, 1), datetime(2018, 1, 1), "YMDH"
    )
    assert got == ["v_2016", "v_2017"]


def test_views_by_time_range_quantum_y_only_misaligned():
    # Y-only quantum with a mid-year start behaves like the reference:
    # year views stamped at the (unaligned) cursor.
    got = tq.views_by_time_range(
        "v", datetime(2016, 6, 15), datetime(2018, 7, 1), "Y"
    )
    assert got == ["v_2016", "v_2017"]
