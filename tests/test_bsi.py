"""BSI integer fields: schema, import, ripple correctness, program
sharing, and the cluster path.

The heart is the randomized property check: Range/Sum/Min/Max results
must be byte-identical to a per-column NumPy reference on data that
includes negatives and the declared min/max boundaries — on the direct
device path, the coalesced path, and across a real 2-node cluster.
"""

import json
import threading
import time

import numpy as np
import pytest

from pilosa_tpu import bsi
from pilosa_tpu.core.frame import FrameError
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import plan
from pilosa_tpu.exec.coalesce import CoalesceScheduler
from pilosa_tpu.exec.executor import Executor, ExecutorError
from pilosa_tpu.ops.bitplane import SLICE_WIDTH
from pilosa_tpu.pql import parse_string

OPS = {
    "<": lambda v, p: v < p,
    "<=": lambda v, p: v <= p,
    "==": lambda v, p: v == p,
    "!=": lambda v, p: v != p,
    ">=": lambda v, p: v >= p,
    ">": lambda v, p: v > p,
}


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


def test_bit_depth_for():
    assert bsi.bit_depth_for(0, 0) == 1
    assert bsi.bit_depth_for(0, 1) == 1
    assert bsi.bit_depth_for(0, 255) == 8
    assert bsi.bit_depth_for(0, 256) == 9
    assert bsi.bit_depth_for(-1000, 10) == 10  # |min| dominates
    assert bsi.bit_depth_for(-3, 1000) == 10


def test_pad_depth_buckets():
    assert bsi.pad_depth(1) == 8
    assert bsi.pad_depth(8) == 8
    assert bsi.pad_depth(9) == 16
    assert bsi.pad_depth(16) == 16
    assert bsi.pad_depth(17) == 24


def test_validate_field():
    with pytest.raises(bsi.BSIError):
        bsi.validate_field("v", 10, -10)  # min > max
    with pytest.raises(bsi.BSIError):
        bsi.validate_field("v", 0, 1 << 63)  # too deep
    bsi.validate_field("v", -5, 5)


def test_pred_row_packing():
    row = bsi.pred_row(-0b1011, 8)
    assert [int(row[k]) for k in range(8)] == [1, 1, 0, 1, 0, 0, 0, 0]
    assert int(row[8]) == 1  # sign flag
    assert int(bsi.pred_row(0b1011, 8)[8]) == 0


@pytest.mark.parametrize(
    "op,value,want",
    [
        ("gt", 1000, ("gt", 255)),   # empty
        ("le", 1000, ("le", 255)),   # everything valued
        ("eq", 1000, ("gt", 255)),   # empty
        ("ne", 1000, ("le", 255)),   # everything valued
        ("lt", -1000, ("lt", -255)),  # empty
        ("ge", -1000, ("ge", -255)),  # everything valued
        ("eq", -1000, ("lt", -255)),  # empty
        ("lt", 100, ("lt", 100)),    # in range: untouched
    ],
)
def test_clamp_predicate(op, value, want):
    assert bsi.clamp_predicate(op, value, 8) == want


def test_field_view_name():
    f = bsi.BSIField(name="qty", min=-5, max=300)
    assert f.view == "field_qty"
    assert f.bit_depth == 9
    assert bsi.is_field_view("field_qty")
    assert not bsi.is_field_view("standard")


# ---------------------------------------------------------------------------
# schema + import on a Holder
# ---------------------------------------------------------------------------


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "h"))
    h.open()
    yield h
    h.close()


def _mkfield(holder, lo=-1000, hi=1000, name="v"):
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists("f")
    f.set_options(range_enabled=True)
    if f.bsi_field(name) is None:
        f.create_field(name, lo, hi)
    return f


def test_field_requires_range_enabled(holder):
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists("f")
    with pytest.raises(FrameError):
        f.create_field("v", 0, 10)


def test_field_persists_across_reopen(holder, tmp_path):
    _mkfield(holder, -7, 300)
    holder.close()
    h2 = Holder(str(tmp_path / "h"))
    h2.open()
    try:
        f = h2.frame("i", "f")
        assert f.range_enabled
        fld = f.bsi_field("v")
        assert (fld.min, fld.max, fld.bit_depth) == (-7, 300, 9)
        assert f.schema_dict()["fields"] == [
            {"name": "v", "type": "int", "min": -7, "max": 300}
        ]
    finally:
        h2.close()


def test_import_value_out_of_range_rejected(holder):
    f = _mkfield(holder, -10, 10)
    with pytest.raises(bsi.BSIError):
        f.import_value("v", [1], [11])
    with pytest.raises(bsi.BSIError):
        f.import_value("v", [1], [-11])
    with pytest.raises(FrameError):
        f.import_value("nope", [1], [1])


def test_import_value_overwrites(holder):
    f = _mkfield(holder)
    ex = Executor(holder)
    f.import_value("v", [5, 9], [1000, -1000])
    (s,) = ex.execute("i", parse_string("Sum(frame=f, field=v)"), None, {})
    assert (s.value, s.count) == (0, 2)
    # Overwrite must clear stale magnitude/sign bits, not OR over them.
    f.import_value("v", [5], [-1])
    f.import_value("v", [9], [3])
    (s,) = ex.execute("i", parse_string("Sum(frame=f, field=v)"), None, {})
    assert (s.value, s.count) == (2, 2)
    (mn,) = ex.execute("i", parse_string("Min(frame=f, field=v)"), None, {})
    assert (mn.value, mn.count) == (-1, 1)
    (mx,) = ex.execute("i", parse_string("Max(frame=f, field=v)"), None, {})
    assert (mx.value, mx.count) == (3, 1)


# ---------------------------------------------------------------------------
# property test: randomized equivalence vs a per-column NumPy reference
# ---------------------------------------------------------------------------


def _rand_data(rng, lo, hi, n, n_slices):
    cols = rng.choice(n_slices * SLICE_WIDTH, size=n, replace=False)
    vals = rng.integers(lo, hi + 1, size=n)
    # Force the declared boundaries (and 0 when representable) into
    # every draw so edge magnitudes are always exercised.
    vals[0], vals[1] = lo, hi
    if lo <= 0 <= hi and n > 2:
        vals[2] = 0
    return cols.astype(np.int64), vals.astype(np.int64)


@pytest.mark.parametrize("use_coalescer", [False, True])
@pytest.mark.parametrize(
    "lo,hi",
    [(-1000, 1000), (0, 255), (-4, 3), (-(1 << 33), 1 << 33)],
)
def test_bsi_matches_numpy_reference(holder, lo, hi, use_coalescer):
    rng = np.random.default_rng(hash((lo, hi)) % (1 << 32))
    f = _mkfield(holder, lo, hi)
    cols, vals = _rand_data(rng, lo, hi, 500, 3)
    f.import_value("v", cols, vals)
    ref = dict(zip(cols.tolist(), vals.tolist()))

    co = CoalesceScheduler() if use_coalescer else None
    ex = Executor(holder, coalescer=co)
    try:
        def run(q):
            return ex.execute("i", parse_string(q), None, {})[0]

        preds = sorted(
            {lo, hi, lo - 1, hi + 1, 0, 1, -1, (lo + hi) // 2,
             int(vals[7]), int(vals[11])}
        )
        for op, pyop in OPS.items():
            for p in preds:
                got = run(f"Count(Range(frame=f, v {op} {p}))")
                want = sum(1 for v in ref.values() if pyop(v, p))
                assert got == want, (op, p, got, want)
        for a, b in [(lo, hi), (-1, 1), (0, 0), (5, 2), (lo - 99, hi + 99)]:
            got = run(f"Count(Range(frame=f, v >< [{a}, {b}]))")
            want = sum(1 for v in ref.values() if a <= v <= b)
            assert got == want, (a, b, got, want)

        s = run("Sum(frame=f, field=v)")
        assert (s.value, s.count) == (sum(ref.values()), len(ref))
        mn, mx = run("Min(frame=f, field=v)"), run("Max(frame=f, field=v)")
        vmin, vmax = min(ref.values()), max(ref.values())
        assert (mn.value, mn.count) == (
            vmin, sum(1 for v in ref.values() if v == vmin))
        assert (mx.value, mx.count) == (
            vmax, sum(1 for v in ref.values() if v == vmax))

        # filtered Sum: only columns matching the child bitmap count
        s = run("Sum(Range(frame=f, v > 0), frame=f, field=v)")
        pos = [v for v in ref.values() if v > 0]
        assert (s.value, s.count) == (sum(pos), len(pos))

        # composability inside set algebra
        got = run("Count(Intersect(Range(frame=f, v >= 0), Range(frame=f, v <= 1)))")
        assert got == sum(1 for v in ref.values() if 0 <= v <= 1)
    finally:
        ex.close()
        if co is not None:
            co.close()


def test_bsi_coalesced_storm_byte_identical(holder):
    rng = np.random.default_rng(3)
    f = _mkfield(holder)
    cols, vals = _rand_data(rng, -1000, 1000, 800, 2)
    f.import_value("v", cols, vals)
    co = CoalesceScheduler()
    ex = Executor(holder, coalescer=co)
    ex_direct = Executor(holder)
    queries = [
        "Count(Range(frame=f, v > 10))",
        "Sum(frame=f, field=v)",
        "Min(frame=f, field=v)",
        "Max(frame=f, field=v)",
        "Count(Range(frame=f, v >< [-100, 100]))",
    ]
    try:
        want = {
            q: ex_direct.execute("i", parse_string(q), None, {})[0]
            for q in queries
        }
        results = {}

        def run(i, q):
            results[i] = ex.execute("i", parse_string(q), None, {})[0]

        ts = [
            threading.Thread(target=run, args=(i, queries[i % len(queries)]))
            for i in range(20)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i, r in results.items():
            assert r == want[queries[i % len(queries)]], i
    finally:
        ex.close()
        ex_direct.close()
        co.close()


# ---------------------------------------------------------------------------
# program sharing per depth bucket
# ---------------------------------------------------------------------------


def test_same_bucket_fields_share_compiled_programs(holder):
    """Two fields of depths 3 and 7 share the depth-8 bucket: after the
    first field's query compiles an op kind, the second field's SAME op
    adds no compiled-program cache entry (the exec.programCache.entries
    gauge stays flat) — a new predicate VALUE doesn't either."""
    idx = holder.create_index_if_not_exists("i")
    f = idx.create_frame_if_not_exists("f")
    f.set_options(range_enabled=True)
    f.create_field("a", 0, 7)    # depth 3 -> bucket 8
    f.create_field("b", -100, 100)  # depth 7 -> bucket 8
    f.import_value("a", [1, 2, 3], [1, 5, 7])
    f.import_value("b", [1, 2, 3], [-5, 0, 99])
    ex = Executor(holder)
    run = lambda q: ex.execute("i", parse_string(q), None, {})[0]  # noqa: E731

    assert run("Count(Range(frame=f, a > 2))") == 2
    warm = plan.program_cache_stats()["total"]
    assert run("Count(Range(frame=f, b > 2))") == 1  # same op, other field
    assert run("Count(Range(frame=f, b > -7))") == 3  # new predicate value
    assert plan.program_cache_stats()["total"] == warm

    (s,) = [run("Sum(frame=f, field=a)")]
    assert (s.value, s.count) == (13, 3)
    warm = plan.program_cache_stats()["total"]
    (s,) = [run("Sum(frame=f, field=b)")]
    assert (s.value, s.count) == (94, 3)
    assert plan.program_cache_stats()["total"] == warm
    ex.close()


# ---------------------------------------------------------------------------
# 2-node cluster: fan-out, import-value replication, partial reduce
# ---------------------------------------------------------------------------


@pytest.fixture
def two_servers(tmp_path):
    from pilosa_tpu.cluster import broadcast as bc
    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net.server import Server

    recv0, recv1 = bc.HTTPBroadcastReceiver(), bc.HTTPBroadcastReceiver()
    b0, b1 = bc.HTTPBroadcaster([]), bc.HTTPBroadcaster([])
    s0 = Server(
        data_dir=str(tmp_path / "n0"),
        cluster=Cluster(replica_n=1),
        broadcaster=b0,
        broadcast_receiver=recv0,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
    )
    s1 = Server(
        data_dir=str(tmp_path / "n1"),
        cluster=Cluster(replica_n=1),
        broadcaster=b1,
        broadcast_receiver=recv1,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
    )
    s0.open()
    s1.open()
    b0.internal_hosts.append(recv1.bound_host)
    b1.internal_hosts.append(recv0.bound_host)
    for c in (s0.cluster, s1.cluster):
        for host in sorted([s0.host, s1.host]):
            if c.node_by_host(host) is None:
                c.add_node(host)
        c.nodes.sort(key=lambda n: n.host)
    yield s0, s1
    s0.close()
    s1.close()


def test_two_node_bsi(two_servers):
    from pilosa_tpu.net.client import InternalClient

    s0, s1 = two_servers
    c0 = InternalClient(s0.host, timeout=10.0)
    c1 = InternalClient(s1.host, timeout=10.0)
    c0.create_index("i")
    c0.create_frame("i", "f", {"rangeEnabled": True})
    c0.create_field("i", "f", "v", -1000, 1000)
    # field fan-out reached the peer (and enabled range there)
    assert c1.frame_fields("i", "f") == [
        {"name": "v", "type": "int", "min": -1000, "max": 1000}
    ]

    rng = np.random.default_rng(11)
    n_slices = 4
    cols = rng.choice(n_slices * SLICE_WIDTH, size=600, replace=False)
    vals = rng.integers(-1000, 1001, size=600)
    vals[0], vals[1] = -1000, 1000
    by_slice = {}
    for c, v in zip(cols.tolist(), vals.tolist()):
        by_slice.setdefault(c // SLICE_WIDTH, []).append((c, v))
    for s, pairs in sorted(by_slice.items()):
        c0.import_value(
            "i", "f", "v", s, [c for c, _ in pairs], [v for _, v in pairs]
        )

    deadline = time.time() + 5.0
    while time.time() < deadline:
        if (
            s0.holder.index("i").max_slice() == n_slices - 1
            and s1.holder.index("i").max_slice() == n_slices - 1
        ):
            break
        time.sleep(0.02)

    ref = dict(zip(cols.tolist(), vals.tolist()))
    # both slices owned by each node contribute; partials reduce on the
    # coordinator — and BOTH coordinators agree.
    for client in (c0, c1):
        got = client.execute_pql("i", "Count(Range(frame=f, v > 100))")
        assert got == sum(1 for v in ref.values() if v > 100)
        got = client.execute_pql("i", "Count(Range(frame=f, v >< [-50, 50]))")
        assert got == sum(1 for v in ref.values() if -50 <= v <= 50)
    # aggregates over JSON (ValCount renders {"value","count"})
    st, data = c0._request(
        "POST", "/index/i/query", body=b"Sum(frame=f, field=v)"
    )
    assert st == 200
    assert json.loads(data)["results"][0] == {
        "value": int(sum(ref.values())),
        "count": len(ref),
    }
    vmin, vmax = min(ref.values()), max(ref.values())
    st, data = c1._request(
        "POST", "/index/i/query", body=b"Min(frame=f, field=v)"
    )
    assert json.loads(data)["results"][0] == {
        "value": vmin,
        "count": sum(1 for v in ref.values() if v == vmin),
    }
    st, data = c1._request(
        "POST", "/index/i/query", body=b"Max(frame=f, field=v)"
    )
    assert json.loads(data)["results"][0] == {
        "value": vmax,
        "count": sum(1 for v in ref.values() if v == vmax),
    }

    # the program-cache gauge is served on /metrics
    st, data = c0._request("GET", "/metrics")
    assert st == 200
    text = data.decode()
    assert "pilosa_exec_programCache_entries" in text

    # field delete fans out too
    c1.delete_field("i", "f", "v")
    assert c0.frame_fields("i", "f") == []


def test_import_value_validation(two_servers):
    from pilosa_tpu.net.client import ClientError, InternalClient

    s0, _ = two_servers
    c0 = InternalClient(s0.host, timeout=10.0)
    c0.create_index("i")
    c0.create_frame("i", "f", {"rangeEnabled": True})
    c0.create_field("i", "f", "v", 0, 100)
    with pytest.raises(ClientError):
        c0.import_value("i", "f", "v", 0, [1], [101])  # out of range
    with pytest.raises(ClientError):
        c0.import_value("i", "f", "nope", 0, [1], [1])  # unknown field


def test_executor_schema_errors(holder):
    idx = holder.create_index_if_not_exists("i")
    idx.create_frame_if_not_exists("f")  # NOT range-enabled
    ex = Executor(holder)
    with pytest.raises(ExecutorError):
        ex.execute("i", parse_string("Count(Range(frame=f, v > 1))"), None, {})
    with pytest.raises(ExecutorError):
        ex.execute("i", parse_string("Sum(frame=f, field=v)"), None, {})
    f = idx.frame("f")
    f.set_options(range_enabled=True)
    with pytest.raises(ExecutorError):  # unknown field
        ex.execute("i", parse_string("Count(Range(frame=f, v > 1))"), None, {})
    ex.close()
