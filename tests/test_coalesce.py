"""Cross-query coalescing tests (exec/coalesce.py + executor wiring).

The acceptance bar: coalesced execution is byte-identical to the
uncoalesced path over the same query mix, concurrent storms ride fewer
launches than queries (occupancy > 1), and a closed scheduler degrades
to direct launches instead of failing queries.
"""

import concurrent.futures
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from pilosa_tpu.cluster.topology import new_cluster
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.coalesce import CoalesceClosed, CoalesceScheduler
from pilosa_tpu.ops.bitplane import SLICE_WIDTH
from pilosa_tpu.pql.parser import parse_string

# A generous accumulation window makes the batching deterministic under
# test: the dispatcher lingers for same-key company instead of racing
# the submitting threads.
WAIT_US = 200_000


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


def _scheduler(**kw):
    kw.setdefault("max_wait_us", WAIT_US)
    return CoalesceScheduler(**kw)


# ---------------------------------------------------------------------------
# scheduler unit (no executor): concat, dedup, padding, close
# ---------------------------------------------------------------------------


def test_concat_batches_one_launch_correct_scatter(rng):
    co = _scheduler()
    try:
        words = 64
        batches = [
            jnp.asarray(
                rng.integers(0, 2**32, size=(1, 1, words), dtype=np.uint32)
            )
            for _ in range(5)
        ]
        expr = ("leaf", 0)
        futs = [co.submit(expr, "count", b) for b in batches]
        results = [f.result(timeout=30) for f in futs]
        for b, (res, info) in zip(batches, results):
            want = int(np.bitwise_count(np.asarray(b)).sum())
            assert res.shape == (1,)
            assert int(res[0]) == want
        # All five distinct 1-row batches accumulated into ONE launch,
        # padded 5 -> 8 with zero rows that are never scattered back.
        infos = {r[1]["launch"] for r in results}
        assert len(infos) == 1
        info = results[0][1]
        assert info["batch_segments"] == 5
        assert info["batch_rows"] == 5
        assert info["pad_rows"] == 3
        snap = co.snapshot()
        assert snap["launches"] == 1 and snap["queries"] == 5
        assert snap["pad_rows"] == 3
    finally:
        co.close()


def test_identity_dedup_shares_one_segment(rng):
    co = _scheduler()
    try:
        words = 32
        batch = jnp.asarray(
            rng.integers(0, 2**32, size=(4, 2, words), dtype=np.uint32)
        )
        expr = ("Intersect", ("leaf", 0), ("leaf", 1))
        futs = [co.submit(expr, "row", batch) for _ in range(6)]
        results = [f.result(timeout=30) for f in futs]
        host = np.asarray(batch)
        want = host[:, 0] & host[:, 1]
        for res, info in results:
            np.testing.assert_array_equal(res, want)
            # One segment, no concatenation, no padding: the launch ran
            # directly on the shared array.
            assert info["batch_segments"] == 1
            assert info["pad_rows"] == 0
        assert co.snapshot()["launches"] < len(futs)
        assert co.snapshot()["max_occupancy"] > 1
    finally:
        co.close()


def test_immediate_dispatch_without_wait_window(rng):
    """max_wait_us=0 (the default): a lone query launches immediately —
    serial queries each get occupancy 1, no added latency."""
    co = CoalesceScheduler(max_wait_us=0)
    try:
        b = jnp.asarray(rng.integers(0, 2**32, size=(1, 1, 16), dtype=np.uint32))
        for _ in range(3):
            res, info = co.submit(("leaf", 0), "count", b).result(timeout=30)
            assert info["batch_queries"] == 1
        assert co.snapshot()["launches"] == 3
    finally:
        co.close()


def test_close_rejects_and_drains(rng):
    co = _scheduler()
    co.close()
    b = jnp.asarray(np.zeros((1, 1, 16), dtype=np.uint32))
    with pytest.raises(CoalesceClosed):
        co.submit(("leaf", 0), "count", b)


def test_launch_error_crosses_future():
    co = CoalesceScheduler(max_wait_us=0)
    try:
        bad = jnp.asarray(np.zeros((1, 1, 16), dtype=np.uint32))
        # A malformed expr reaches the launch and must fail THIS future,
        # not wedge the dispatcher.
        fut = co.submit(("Bogus",), "count", bad)
        with pytest.raises(Exception):
            fut.result(timeout=30)
        # The dispatcher survives and serves the next submission.
        ok = co.submit(("leaf", 0), "count", bad).result(timeout=30)
        assert int(ok[0][0]) == 0
    finally:
        co.close()


# ---------------------------------------------------------------------------
# executor integration
# ---------------------------------------------------------------------------


def _seed(holder):
    idx = holder.create_index("i")
    f = idx.create_frame("f", cache_size=64)
    bits = [
        (1, 0), (1, 3), (1, SLICE_WIDTH + 1), (1, 2 * SLICE_WIDTH + 5),
        (2, 3), (2, SLICE_WIDTH + 1), (2, SLICE_WIDTH + 9),
        (3, 7), (3, 2 * SLICE_WIDTH + 5),
    ]
    for row, col in bits:
        f.set_bit("standard", row, col)
    return f


MIX = [
    "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))",
    "Count(Union(Bitmap(rowID=1, frame=f), Bitmap(rowID=3, frame=f)))",
    "Bitmap(rowID=1, frame=f)",
    "Union(Bitmap(rowID=2, frame=f), Bitmap(rowID=3, frame=f))",
    "TopN(frame=f, n=2)",
    "Count(Bitmap(rowID=3, frame=f))",
]


def _canon(result):
    """Comparable form of one query result (ints, bit lists, pairs)."""
    if hasattr(result, "bits"):
        return ("bits", tuple(result.bits()))
    if isinstance(result, list):
        return ("pairs", tuple((p.id, p.count) for p in result))
    return ("val", int(result))


def test_coalesce_on_off_identical_results(holder):
    _seed(holder)
    c = new_cluster(1)
    plain = Executor(holder, host=c.nodes[0].host, cluster=c)
    expected = [
        _canon(plain.execute("i", parse_string(q))[0]) for q in MIX
    ]
    plain.close()

    co = _scheduler()
    ex = Executor(holder, host=c.nodes[0].host, cluster=c, coalescer=co)
    try:
        # Serial pass.
        got = [_canon(ex.execute("i", parse_string(q))[0]) for q in MIX]
        assert got == expected
        # Concurrent pass: every thread runs the whole mix; results must
        # stay byte-identical under coalesced launches.
        def run_mix(_):
            return [_canon(ex.execute("i", parse_string(q))[0]) for q in MIX]

        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            for got in pool.map(run_mix, range(8)):
                assert got == expected
    finally:
        ex.close()
        co.close()


def test_concurrent_storm_occupancy_above_one(holder):
    _seed(holder)
    c = new_cluster(1)
    co = _scheduler()
    ex = Executor(holder, host=c.nodes[0].host, cluster=c, coalescer=co)
    try:
        pq = parse_string(
            "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))"
        )
        (want,) = ex.execute("i", pq)  # warm the batch cache
        before = co.snapshot()
        n = 24
        barrier = threading.Barrier(12)

        def one(_):
            barrier.wait(timeout=30)
            (got,) = ex.execute("i", pq)
            assert int(got) == int(want)

        with concurrent.futures.ThreadPoolExecutor(12) as pool:
            list(pool.map(one, range(n)))
        snap = co.snapshot()
        launches = snap["launches"] - before["launches"]
        queries = snap["queries"] - before["queries"]
        assert queries == n
        assert launches < queries
        assert queries / launches > 1.0
    finally:
        ex.close()
        co.close()


def test_closed_coalescer_falls_back_to_direct_path(holder):
    _seed(holder)
    c = new_cluster(1)
    co = CoalesceScheduler(max_wait_us=0)
    co.close()
    ex = Executor(holder, host=c.nodes[0].host, cluster=c, coalescer=co)
    try:
        (n,) = ex.execute(
            "i",
            parse_string(
                "Count(Union(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))"
            ),
        )
        assert int(n) == 5  # {0, 3, S+1, S+9, 2S+5}
    finally:
        ex.close()


def test_coalesced_single_slice_queries_share_launch(holder):
    """Distinct single-slice entries with the same compile key take the
    CONCAT path end to end through the executor."""
    _seed(holder)
    c = new_cluster(1)
    co = _scheduler()
    ex = Executor(holder, host=c.nodes[0].host, cluster=c, coalescer=co)
    try:
        queries = [
            (parse_string(f"Count(Bitmap(rowID={r}, frame=f))"), [0])
            for r in (1, 2, 3)
        ]
        # Warm each entry's batch cache serially (separate cache keys).
        want = [int(ex.execute("i", q, slices=s)[0]) for q, s in queries]
        before = co.snapshot()
        barrier = threading.Barrier(len(queries))

        def one(i):
            q, s = queries[i]
            barrier.wait(timeout=30)
            return int(ex.execute("i", q, slices=s)[0])

        with concurrent.futures.ThreadPoolExecutor(len(queries)) as pool:
            got = list(pool.map(one, range(len(queries))))
        assert got == want
        snap = co.snapshot()
        assert snap["queries"] - before["queries"] == len(queries)
        assert snap["launches"] - before["launches"] < len(queries)
    finally:
        ex.close()
        co.close()
